"""Sequential-commit batch scheduling: one launch must equal the one-pod-at-
a-time golden loop (schedule -> commit -> schedule ...)."""

import numpy as np
import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.cpuref import CPUScheduler
from kubernetes_tpu.models.batched import encode_batch_ports, make_sequential_scheduler

from fixtures import TEST_DIMS, ZONE_KEY, make_node, make_pod, random_cluster, random_pending_pod


def golden_sequential(nodes, existing, services, pending):
    """Reference loop: schedule one pod, commit it, repeat
    (scheduleOne semantics, scheduler.go:438-593)."""
    placed = list(existing)
    out = []
    last = 0
    for pod in pending:
        golden = CPUScheduler(nodes, placed, services)
        host, _ = golden.schedule(pod, last_index=last)
        last += 1
        out.append(host)
        if host is not None:
            import dataclasses

            committed = dataclasses.replace(
                pod, spec=dataclasses.replace(pod.spec, node_name=host)
            )
            placed.append(committed)
    return out


def run_device_sequential(nodes, existing, services, pending):
    enc = SnapshotEncoder(TEST_DIMS)
    for n in nodes:
        enc.add_node(n)
    for p in existing:
        enc.add_pod(p)
    for ns, sel in services:
        enc.add_spread_selector(ns, sel)
    batch = enc.encode_pods(pending)
    ports = encode_batch_ports(enc, pending)
    cluster = enc.snapshot()
    unsched = enc.interner.intern("node.kubernetes.io/unschedulable")
    fn = make_sequential_scheduler(
        unsched_taint_key=unsched, zone_key_id=enc.zone_key
    )
    hosts, new_cluster = fn(cluster, batch, ports, np.int32(0))
    hosts = np.asarray(hosts)
    row_names = {row: name for name, row in enc.node_rows.items()}
    return [
        row_names[int(h)] if int(h) >= 0 else None for h in hosts[: len(pending)]
    ], np.asarray(new_cluster.requested)


def test_sequential_commits_resources():
    # each node fits exactly two 400m pods on 1 cpu; commits inside the batch
    # must make later pods see earlier placements
    nodes = [make_node("n1", cpu="1", mem="8Gi"), make_node("n2", cpu="1", mem="8Gi")]
    pending = [make_pod(f"p{i}", cpu="400m", mem="128Mi") for i in range(4)]
    got, _ = run_device_sequential(nodes, [], [], pending)
    want = golden_sequential(nodes, [], [], pending)
    assert got == want
    assert got.count("n1") == 2 and got.count("n2") == 2


def test_sequential_unschedulable_tail():
    nodes = [make_node("n1", cpu="1", mem="1Gi", pods=2)]
    pending = [make_pod(f"p{i}", cpu="300m", mem="128Mi") for i in range(4)]
    got, _ = run_device_sequential(nodes, [], [], pending)
    want = golden_sequential(nodes, [], [], pending)
    assert got == want
    assert got[2] is None and got[3] is None  # pod-count cap = 2


def test_sequential_ports_within_batch():
    nodes = [make_node("n1"), make_node("n2"), make_node("n3")]
    pending = [
        make_pod(f"p{i}", ports=[{"hostPort": 8080, "protocol": "TCP"}])
        for i in range(4)
    ]
    got, _ = run_device_sequential(nodes, [], [], pending)
    want = golden_sequential(nodes, [], [], pending)
    assert got == want
    # only three nodes can hold hostPort 8080
    assert sorted(h for h in got if h) == ["n1", "n2", "n3"] and got.count(None) == 1


def test_sequential_spreading_within_batch():
    nodes = [make_node(f"n{i}") for i in range(3)]
    services = [("default", {"app": "web"})]
    pending = [make_pod(f"w{i}", labels={"app": "web"}) for i in range(6)]
    got, _ = run_device_sequential(nodes, [], services, pending)
    want = golden_sequential(nodes, [], services, pending)
    assert got == want
    # spreading should land 2 per node
    from collections import Counter

    assert sorted(Counter(got).values()) == [2, 2, 2]


@pytest.mark.parametrize("seed", range(3))
def test_sequential_randomized(seed):
    """Follow the device trajectory; each placement must be feasible per the
    golden and within the float-blend tolerance (PARITY.md delta 1: three
    priorities may each drift ±1, weights 1) of the golden best score."""
    import dataclasses

    rng = np.random.default_rng(7000 + seed)
    nodes, existing, services = random_cluster(
        rng, n_nodes=8, n_pods=16, with_affinity=False
    )
    pending = [
        random_pending_pod(rng, i, with_affinity=False) for i in range(10)
    ]
    got, _ = run_device_sequential(nodes, existing, services, pending)
    placed = list(existing)
    for pod, host in zip(pending, got):
        golden = CPUScheduler(nodes, placed, services)
        feasible = {n.name for n in nodes if golden.fits(pod, n)}
        if host is None:
            assert not feasible, f"{pod.name}: device said unschedulable, golden fits {feasible}"
            continue
        assert host in feasible, f"{pod.name}: device placed on infeasible {host}"
        totals = golden.total_scores(pod)
        best = max(totals[n] for n in feasible)
        assert totals[host] >= best - 3.0, (
            f"{pod.name}: device host {host} score {totals[host]} vs best {best}"
        )
        placed.append(
            dataclasses.replace(pod, spec=dataclasses.replace(pod.spec, node_name=host))
        )


def test_encode_pods_local_row_sharing_differential():
    """The call-local row cache (encoder._pod_local_key) must be
    invisible: a randomized mixed population (plain / affinity / ports /
    tolerations, repeated and unique shapes) encodes bit-identically with
    the cache disabled."""
    import dataclasses as _dc

    def build():
        enc = SnapshotEncoder(TEST_DIMS)
        for i in range(16):
            enc.add_node(make_node(
                f"n{i}", cpu="8", mem="32Gi",
                labels={ZONE_KEY: f"z{i % 3}", "tier": "a" if i % 2 else "b"},
            ))
        enc.add_spread_selector("default", {"app": "web"})
        # committed pods with terms => term_groups non-empty (the
        # state-dependent regime the cross-call cache refuses)
        enc.add_pod(make_pod(
            "committed", cpu="100m", labels={"app": "web"},
            node_name="n0",
            affinity={"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": ZONE_KEY}]}},
        ))
        rng = np.random.default_rng(42)
        pods = []
        for i in range(60):
            kind = int(rng.integers(0, 5))
            app = f"app-{int(rng.integers(0, 3))}"
            if kind == 0:
                pods.append(make_pod(f"p{i}", cpu="100m", mem="128Mi",
                                     labels={"app": app}))
            elif kind == 1:
                pods.append(make_pod(
                    f"p{i}", cpu="200m", labels={"app": app},
                    affinity={"podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [{
                            "labelSelector": {"matchLabels": {"app": app}},
                            "topologyKey": ZONE_KEY}]}}))
            elif kind == 2:
                pods.append(make_pod(
                    f"p{i}", cpu="50m", labels={"app": app},
                    affinity={"podAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [{
                            "labelSelector": {"matchLabels": {"app": "web"}},
                            "topologyKey": ZONE_KEY}]}}))
            elif kind == 3:
                pods.append(make_pod(f"p{i}", cpu="50m", labels={"app": app},
                                     ports=[{"hostPort": 8000 + i % 4}]))
            else:
                pods.append(make_pod(
                    f"p{i}", cpu="50m", labels={"app": app},
                    tolerations=[{"key": "dedicated", "operator": "Exists",
                                  "effect": "NoSchedule"}]))
        return enc, pods

    enc1, pods1 = build()
    b1 = enc1.encode_pods(pods1)
    enc2, pods2 = build()
    orig = SnapshotEncoder._pod_local_key
    SnapshotEncoder._pod_local_key = lambda self, pod: None
    try:
        b2 = enc2.encode_pods(pods2)
    finally:
        SnapshotEncoder._pod_local_key = orig
    for f in _dc.fields(b1):
        v1, v2 = getattr(b1, f.name), getattr(b2, f.name)
        if hasattr(v1, "shape"):
            np.testing.assert_array_equal(
                np.asarray(v1), np.asarray(v2), err_msg=f.name)
