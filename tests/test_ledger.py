"""Decision ledger + per-plugin attribution (ISSUE 7).

Covers the four contracts the tentpole names:

  * attribution-flag bit-identity: the sequential engine's winners are
    unchanged by the attribution flag (it is output-only), and the
    attribution itself names the right predicates with the right node
    counts;
  * unschedulable explain: FailedScheduling events and the
    kubernetes-tpu.io/unschedulable-reason annotation name the dominant
    failing predicate with per-reason node counts, and the
    scheduler_unschedulable_reasons_total{plugin=} family moves;
  * record -> replay determinism: live-recorded cycles (fault injection
    included, both engines) replay through the recorded engine to
    bit-identical winners, via runtime/ledger.replay AND
    Scheduler.replay_cycle;
  * bounded recording: the writer queue and the max-cycles cap drop
    records without ever blocking a scheduling cycle, counted in
    scheduler_ledger_dropped_total.
"""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.schema import (
    PRED_INDEX,
    REASON_EXTENDER,
    reason_name,
)
from kubernetes_tpu.codec.transfer import apply_snapshot_delta, snapshot_delta
from kubernetes_tpu.models.batched import (
    encode_batch_ports,
    make_sequential_scheduler,
)
from kubernetes_tpu.runtime import ledger as ledger_mod
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.chaos import Disruptions
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.health import start_health_server
from kubernetes_tpu.runtime.ledger import (
    DecisionLedger,
    bounded_json,
    explain_unschedulable,
    read_ledger,
    replay,
)
from kubernetes_tpu.runtime.queue import PodBackoff, PriorityQueue
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.utils import metrics as m

from fixtures import make_node, make_pod

ZONE = "failure-domain.beta.kubernetes.io/zone"


def _mini(tmp_path=None, engine="speculative", attribution=False,
          ledger=None, n_nodes=6, **cfg_kw):
    cache = SchedulerCache()
    queue = PriorityQueue(backoff=PodBackoff(initial=0.01, max_duration=0.05))
    cfg = SchedulerConfig(
        disable_preemption=True, engine=engine, attribution=attribution,
        **cfg_kw,
    )
    sched = Scheduler(
        cache=cache, queue=queue, binder=lambda p, n: True, config=cfg,
        ledger=ledger,
    )
    for i in range(n_nodes):
        taints = (
            [{"key": "ded", "value": "x", "effect": "NoSchedule"}]
            if i < 2 else []
        )
        cache.add_node(make_node(
            f"n{i}", cpu="4", mem="8Gi",
            labels={ZONE: f"z-{i % 2}"}, taints=taints,
        ))
    return sched, cache, queue


# ------------------------------------------------------ snapshot deltas


def test_snapshot_delta_roundtrip_and_nan_safety():
    enc = SnapshotEncoder()
    for i in range(5):
        enc.add_node(make_node(
            f"n{i}", cpu="4", mem="8Gi",
            # numeric label -> a real NaN-bearing label_nums column
            labels={"rank": str(i), "tier": "a"},
        ))
    snap0 = enc.snapshot()
    enc.add_pod(make_pod("p0", cpu="500m", node_name="n2"))
    enc.add_node(make_node("n5", cpu="8", mem="16Gi"))
    snap1 = enc.snapshot()

    full = snapshot_delta(None, snap0)
    rebuilt0 = apply_snapshot_delta(None, full, cls=type(snap0))
    d01 = snapshot_delta(snap0, snap1)
    rebuilt1 = apply_snapshot_delta(rebuilt0, d01)
    import dataclasses

    for f in dataclasses.fields(snap1):
        a = np.asarray(getattr(snap1, f.name))
        b = np.asarray(getattr(rebuilt1, f.name))
        assert a.shape == b.shape and a.dtype == b.dtype, f.name
        if a.dtype.kind == "f":
            assert np.array_equal(a, b, equal_nan=True), f.name
        else:
            assert np.array_equal(a, b), f.name
    # unchanged-field identity means NaN-bearing float fields don't
    # balloon the delta: an untouched-row field records at most its
    # dirty rows, never a spurious full diff
    enc.add_pod(make_pod("p1", cpu="100m", node_name="n0"))
    snap2 = enc.snapshot()
    d12 = snapshot_delta(snap1, snap2)
    assert "label_nums" not in d12  # node labels untouched by a pod add
    mode, idx, _vals = d12["requested"]
    assert mode == "rows" and list(idx) == [0]


def test_first_ledger_record_must_be_full():
    enc = SnapshotEncoder()
    enc.add_node(make_node("n0", cpu="1", mem="1Gi"))
    snap = enc.snapshot()
    with pytest.raises(ValueError):
        apply_snapshot_delta(
            None, {"requested": ("full", snap.requested)}, cls=type(snap)
        )


# ------------------------------------------------- engine attribution


def _engine_pair(enc):
    key = enc.interner.intern("node.kubernetes.io/unschedulable")
    kw = dict(unsched_taint_key=key, zone_key_id=enc.getzone_key)
    return (
        make_sequential_scheduler(**kw),
        make_sequential_scheduler(**kw, attribution=True),
    )


def test_attribution_flag_bit_identity_and_reason_counts():
    enc = SnapshotEncoder()
    for i in range(8):
        taints = (
            [{"key": "ded", "value": "x", "effect": "NoSchedule"}]
            if i < 3 else []
        )
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi", taints=taints))
    pods = [make_pod("fits", cpu="100m"), make_pod("never", cpu="64")]
    batch = enc.encode_pods(pods)
    ports = encode_batch_ports(enc, pods)
    cluster = enc.snapshot()
    plain, attributed = _engine_pair(enc)
    h0, _ = plain(cluster, batch, ports, np.int32(0))
    h1, _, attr = attributed(cluster, batch, ports, np.int32(0))
    assert np.array_equal(np.asarray(h0), np.asarray(h1)), (
        "attribution flag changed the winners"
    )
    rc = np.asarray(attr.reason_counts)
    # pod 0 fits: only the 3 tainted nodes reject it
    assert rc[0, PRED_INDEX["PodToleratesNodeTaints"]] == 3
    assert rc[0].sum() == 3
    # pod 1 can't fit anywhere: resources first-fail on all 8 (the
    # aggregate GeneralPredicates row must NOT swallow the attribution)
    assert rc[1, PRED_INDEX["PodFitsResources"]] == 8
    assert rc[1, PRED_INDEX["GeneralPredicates"]] == 0
    # top-k: pod 0's winner leads its own breakdown and the per-plugin
    # addends sum to the selected score
    tn = np.asarray(attr.top_nodes)
    ts = np.asarray(attr.top_scores)
    tc = np.asarray(attr.top_components)
    assert tn[0, 0] == int(np.asarray(h0)[0])
    assert ts[0, 0] == pytest.approx(tc[0, 0].sum(), rel=1e-5)
    assert (tn[1] == -1).all()  # nothing feasible -> no top-k rows


def test_attribution_extra_mask_attributes_to_extender():
    enc = SnapshotEncoder()
    for i in range(4):
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    pods = [make_pod("vetoed", cpu="100m")]
    batch = enc.encode_pods(pods)
    ports = encode_batch_ports(enc, pods)
    cluster = enc.snapshot()
    _, attributed = _engine_pair(enc)
    extra = np.zeros((batch.n_pods, cluster.n_nodes), bool)  # veto all
    hosts, _, attr = attributed(
        cluster, batch, ports, np.int32(0), None, extra, None, None
    )
    assert int(np.asarray(hosts)[0]) == -1
    rc = np.asarray(attr.reason_counts)[0]
    assert rc[REASON_EXTENDER] == 4 and rc.sum() == 4
    dominant, msg = explain_unschedulable(rc)
    assert dominant == "ExtenderFilter"
    assert "extender or plugin" in msg and msg.startswith("0/4 nodes")


# ------------------------------------------------- unschedulable explain


def test_unschedulable_event_annotation_and_metric():
    sched, cache, queue = _mini(attribution=True, decision_ledger=True)
    before = m.UNSCHEDULABLE_REASONS.value(plugin="PodFitsResources")
    big = make_pod("big", cpu="64")
    queue.add(big)
    queue.add(make_pod("ok", cpu="100m"))
    sched.run_once(timeout=0.3)
    msgs = [
        e.message for e in sched.recorder.events()
        if e.reason == "FailedScheduling"
    ]
    assert len(msgs) == 1
    # per-reason node counts, dominant first: 4 untainted nodes fail on
    # resources, 2 tainted nodes fail on taints (taints come after
    # resources in PREDICATE_ORDER... but tainted nodes ALSO lack cpu;
    # resources first-fails everywhere)
    assert "6 Insufficient resources" in msgs[0]
    assert msgs[0].startswith("0/6 nodes are available: ")
    ann = big.metadata.annotations[Scheduler.UNSCHED_REASON_ANNOTATION]
    assert ann == msgs[0]
    assert (
        m.UNSCHEDULABLE_REASONS.value(plugin="PodFitsResources")
        == before + 1
    )
    # the decisions ring carries the same explanation, trace-linked
    entries = sched.ledger.decisions()
    unsched = [
        p for e in entries for p in e["pods"] if p["node"] is None
    ]
    assert unsched and unsched[0]["reason"] == "PodFitsResources"
    assert all(e["trace_id"] for e in entries)


def test_explain_names_dominant_taint_predicate():
    # pods that FIT resource-wise: only the tainted nodes reject them
    sched, cache, queue = _mini(attribution=True)
    # consume nothing; make a pod that fits everywhere but is repelled
    # by the 2 tainted nodes AND pinned to one of them by nodeName
    pinned = make_pod("pinned", cpu="100m", node_name="n0")
    queue.add(pinned)
    sched.run_once(timeout=0.3)
    ann = pinned.metadata.annotations[Scheduler.UNSCHED_REASON_ANNOTATION]
    # 5 nodes fail the hostname pin (PodFitsHost), the pinned node n0
    # fails its taint
    assert "5 node(s) didn't match the requested hostname" in ann
    assert "1 node(s) had taints that the pod didn't tolerate" in ann


# --------------------------------------------------- record -> replay


def _run_workload(sched, queue, n=10):
    for i in range(n):
        queue.add(make_pod(
            f"w-{i}", cpu="200m", mem="128Mi",
            labels={"app": f"d-{i % 3}"},
        ))
    deadline = time.monotonic() + 30
    while queue.has_schedulable() and time.monotonic() < deadline:
        sched.run_once(timeout=0.05)
    sched.flush_pipeline()


@pytest.mark.parametrize("engine", ["speculative", "sequential"])
def test_record_replay_bit_identity(tmp_path, engine):
    path = str(tmp_path / "decisions.ledger")
    ledger = DecisionLedger(path=path)
    sched, cache, queue = _mini(engine=engine, ledger=ledger,
                                batch_size=4)
    queue.add(make_pod("never", cpu="64"))  # an unschedulable too
    _run_workload(sched, queue, n=10)
    assert ledger.flush(10)
    header, recs = read_ledger(path)
    assert header["engine"] == engine
    assert len(recs) >= 2 and sum(r["n_pods"] for r in recs) >= 11
    out = replay(path)
    assert out["bit_identical"], out
    assert out["engine"] == engine
    # the in-process path agrees record by record
    for rec in recs:
        sched.replay_cycle(rec)


@pytest.mark.chaos
@pytest.mark.parametrize("engine", ["speculative", "sequential"])
def test_record_replay_bit_identity_under_fault_injection(tmp_path, engine):
    """Cycles recorded WHILE the device faults (transient retries, and a
    breaker-tripping persistent fault whose batches the CPU engine
    serves) replay to bit-identical winners once the faults clear: the
    ledger records the inputs of the launch that COMMITTED, whatever the
    recovery path was."""
    path = str(tmp_path / "chaos.ledger")
    ledger = DecisionLedger(path=path)
    sched, cache, queue = _mini(
        engine=engine, ledger=ledger, batch_size=4,
        device_retry_max=2, breaker_failure_threshold=3,
        breaker_open_s=0.02, cpu_fallback=True,
    )
    dis = Disruptions(LocalCluster())
    try:
        dis.device_transient(count=2)
        _run_workload(sched, queue, n=6)
        dis.clear_device_faults()
        dis.device_lost(count=4)
        _run_workload(sched, queue, n=6)
    finally:
        dis.clear_device_faults()
    # let the breaker recover and schedule a clean tail
    time.sleep(0.03)
    _run_workload(sched, queue, n=4)
    assert ledger.flush(10)
    _, recs = read_ledger(path)
    assert len(recs) >= 3
    engines = {r["engine"] for r in recs}
    out = replay(path)
    assert out["bit_identical"], (engines, out)


# ----------------------------------------------------------- bounds


def test_ledger_max_cycles_cap_drops(tmp_path):
    path = str(tmp_path / "capped.ledger")
    ledger = DecisionLedger(path=path, max_cycles=2)
    sched, cache, queue = _mini(ledger=ledger, batch_size=1)
    for i in range(5):
        queue.add(make_pod(f"p{i}", cpu="100m"))
        sched.run_once(timeout=0.2)
    assert ledger.flush(10)
    assert ledger.dropped_total >= 3
    _, recs = read_ledger(path)
    assert len(recs) == 2
    # the ring keeps serving recent decisions past the file cap
    assert len(ledger.decisions()) == 5


def test_ledger_queue_overflow_drops_without_blocking(tmp_path, monkeypatch):
    path = str(tmp_path / "slow.ledger")
    ledger = DecisionLedger(path=path, queue_capacity=2)
    orig = ledger._serialize

    def slow_serialize(inputs, outcome):
        time.sleep(0.05)
        return orig(inputs, outcome)

    monkeypatch.setattr(ledger, "_serialize", slow_serialize)
    sched, cache, queue = _mini(ledger=ledger, batch_size=1)
    t0 = time.monotonic()
    for i in range(10):
        queue.add(make_pod(f"p{i}", cpu="100m"))
        sched.run_once(timeout=0.2)
    submit_wall = time.monotonic() - t0
    assert ledger.flush(10)
    assert ledger.dropped_total > 0, "queue never overflowed"
    assert ledger.cycles_total == 10  # every cycle still ring-recorded
    _, recs = read_ledger(path)
    assert 0 < len(recs) < 10
    # a full writer queue must never block the scheduling thread for
    # the duration of a write (10 cycles << 10 * 50ms serialization)
    assert submit_wall < 0.4, f"recording blocked the hot path: {submit_wall}s"
    # dropped records force the next delta chain full, so the file
    # still reconstructs and replays
    assert replay(path)["bit_identical"]


# --------------------------------------------------------- endpoints


def test_debug_decisions_endpoints_limit_and_cap():
    sched, cache, queue = _mini(attribution=True, decision_ledger=True)
    for i in range(5):
        queue.add(make_pod(f"p{i}", cpu="100m"))
        sched.run_once(timeout=0.2)
    srv = start_health_server()
    try:
        h, p = srv.address
        with urllib.request.urlopen(
            f"http://{h}:{p}/debug/decisions", timeout=5
        ) as r:
            assert r.headers.get("Content-Type") == "application/json"
            body = json.loads(r.read())
        assert len(body["decisions"]) == 5
        for e in body["decisions"]:
            assert e["trace_id"] and e["pods"]
        with urllib.request.urlopen(
            f"http://{h}:{p}/debug/decisions?limit=2", timeout=5
        ) as r:
            assert len(json.loads(r.read())["decisions"]) == 2
        with urllib.request.urlopen(
            f"http://{h}:{p}/debug/traces?limit=1", timeout=5
        ) as r:
            t = json.loads(r.read())
        cycles = [
            e for e in t["traceEvents"] if e["name"] == "schedule_cycle"
        ]
        assert len(cycles) == 1
    finally:
        srv.stop()
    # apiserver twin, inflight-limiter exempt by being served at all
    from kubernetes_tpu.apiserver import APIServer

    srv = APIServer(cluster=LocalCluster()).start()
    try:
        with urllib.request.urlopen(
            f"{srv.url}/debug/decisions?limit=3", timeout=5
        ) as r:
            assert len(json.loads(r.read())["decisions"]) == 3
    finally:
        srv.stop()


def test_bounded_json_halves_to_fit_cap():
    entries = [{"i": i, "pad": "x" * 100} for i in range(64)]

    def render(lim):
        return entries[-lim:] if lim is not None else entries

    body = bounded_json(render, None, cap=1200)
    assert len(body) <= 1200
    assert 0 < len(json.loads(body)) < 64
    # a single oversized entry degrades to the well-formed error stub
    huge = bounded_json(lambda lim: [{"pad": "y" * 4096}], None, cap=128)
    assert json.loads(huge)["truncated"] is True


def test_decisions_cross_link_flight_recorder_trace_ids():
    from kubernetes_tpu.runtime.flightrecorder import FlightRecorder

    fr = FlightRecorder()
    sched, cache, queue = _mini(decision_ledger=True)
    sched.flight_recorder = fr
    queue.add(make_pod("joined", cpu="100m"))
    sched.run_once(timeout=0.2)
    ring_ids = {s.trace_id for s in fr.spans()}
    for e in sched.ledger.decisions():
        assert e["trace_id"] in ring_ids


def test_unschedulable_annotation_cleared_on_later_bind():
    """A pod that failed (annotation stamped) and later binds must not
    keep claiming it is unschedulable."""
    # one node, tainted: the pod is rejected with a countable reason
    sched, cache, queue = _mini(attribution=True, n_nodes=1)
    pod = make_pod("later", cpu="100m")
    queue.add(pod)
    sched.run_once(timeout=0.2)  # taint rejects: unschedulable, stamped
    assert Scheduler.UNSCHED_REASON_ANNOTATION in pod.metadata.annotations
    cache.add_node(make_node("late-node", cpu="4", mem="8Gi"))
    queue.move_all_to_active()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        sched.run_once(timeout=0.05)
        if any(r.node for r in sched.results):
            break
    assert any(r.node for r in sched.results), "pod never bound"
    assert Scheduler.UNSCHED_REASON_ANNOTATION not in pod.metadata.annotations


def test_gang_and_prewarm_survive_attribution_engine():
    """The attribution variant returns a third output; the gang launch
    and prewarm consume the same _schedule_fn and must index, not
    unpack (regression: ValueError 'too many values to unpack')."""
    from kubernetes_tpu.runtime.flightrecorder import FlightRecorder

    sched, cache, queue = _mini(attribution=True, n_nodes=4, batch_size=8)
    sched.flight_recorder = FlightRecorder()  # isolate from the global ring
    sched.prewarm(widths=[2])
    g = {Scheduler.POD_GROUP_LABEL: "g1",
         Scheduler.POD_GROUP_MIN_MEMBER: "2"}
    for i in range(2):
        queue.add(make_pod(f"g1-{i}", cpu="100m", labels=dict(g)))
    queue.add(make_pod("plain", cpu="100m"))  # one plain cycle too
    deadline = time.monotonic() + 10
    placed = 0
    while time.monotonic() < deadline and placed < 3:
        placed += sched.run_once(timeout=0.05)
    assert placed == 3, "gang failed to schedule under attribution"
    # the spans and the ledger ring agree the sequential engine served
    # the plain cycles (attribution forces it whatever config.engine is)
    spans = [s for s in sched.flight_recorder.spans()
             if s.name == "schedule_cycle"]
    assert spans and all(
        s.attrs.get("engine") in ("sequential", "cpu") for s in spans
    ), [s.attrs.get("engine") for s in spans]
