"""kubeadm analog (cmd/kubeadm): init brings up a control plane + mints a
token, join validates the token and registers a heartbeating node."""

import json
import threading

from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver.admission import default_admission_chain
from kubernetes_tpu.cmd import kubeadm
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.utils import klog


def test_init_writes_kubeconfig_and_join_flow(tmp_path):
    kc = str(tmp_path / "admin.conf")
    rc = kubeadm.main([
        "--platform", "cpu",
        "init", "--port", "0", "--kubeconfig", kc, "--one-shot",
    ])
    assert rc == 0
    cfg = json.load(open(kc))
    assert cfg["server"].startswith("http://")
    assert cfg["token"]                      # admin credential
    assert "." in cfg["bootstrap-token"]     # kubeadm token format


def test_join_token_validation_and_node_registration(tmp_path):
    from kubernetes_tpu.apiserver.auth import (
        RBACAuthorizer,
        TokenAuthenticator,
        ensure_bootstrap_policy,
    )

    cluster = LocalCluster()
    ensure_bootstrap_policy(cluster)
    authn = TokenAuthenticator(cluster)
    authn.add_static("admintok", "kubernetes-admin", ("system:masters",))
    srv = APIServer(
        cluster=cluster, admission=default_admission_chain(cluster),
        authenticator=authn, authorizer=RBACAuthorizer(cluster),
    ).start()
    try:
        token = kubeadm._mint_token()
        kubeadm._store_token(srv.url, token, admin_token="admintok")
        # bad token rejected
        rc = kubeadm.main([
            "join", "--server", srv.url, "--token", "aaaaaa.0000000000000000",
            "--node-name", "evil", "--one-shot",
        ])
        assert rc == 1
        assert cluster.get("nodes", "", "evil") is None
        # good token registers a Ready node + lease
        rc = kubeadm.main([
            "join", "--server", srv.url, "--token", token,
            "--node-name", "worker-1", "--one-shot",
        ])
        assert rc == 0
        node = cluster.get("nodes", "", "worker-1")
        assert node is not None
        assert node.status.conditions.get("Ready") == "True"
        assert cluster.get("leases", "kube-node-lease", "worker-1") is not None
        # token list shows the minted id
        import io
        import sys as _sys

        buf = io.StringIO()
        old = _sys.stdout
        _sys.stdout = buf
        try:
            kubeadm.main(["token", "list", "--server", srv.url,
                          "--token", "admintok"])
        finally:
            _sys.stdout = old
        assert token.split(".")[0] in buf.getvalue()
    finally:
        srv.stop()


def test_klog_levels(capsys):
    klog.set_verbosity(1)
    klog.V(1).infof("visible %d", 1)
    klog.V(3).infof("hidden %d", 3)
    assert bool(klog.V(1)) and not bool(klog.V(3))
    klog.set_verbosity(0)


def test_init_secure_serves_https_and_issues_certs(tmp_path, monkeypatch):
    """kubeadm init --secure: HTTPS plane, CA on disk + in the
    kube-root-ca Secret, kubeconfig carries certificate-authority, and a
    join over the secure plane gets a REAL client cert from the CSR flow
    (VERDICT r3 #8 implemented)."""
    kc = str(tmp_path / "admin.conf")
    cert_dir = str(tmp_path / "pki")
    rc = kubeadm.main([
        "--platform", "cpu",
        "init", "--port", "0", "--kubeconfig", kc,
        "--secure", "--cert-dir", cert_dir, "--one-shot",
    ])
    assert rc == 0
    cfg = json.load(open(kc))
    assert cfg["server"].startswith("https://")
    assert cfg["certificate-authority"].endswith("ca.crt")
    import os

    assert os.path.exists(cfg["certificate-authority"])
    # clients trust the plane through KTPU_CACERT (one-shot already tore
    # the server down; this validates wiring, not liveness)
    monkeypatch.setenv("KTPU_CACERT", cfg["certificate-authority"])
