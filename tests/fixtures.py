"""Test object makers + randomized cluster generator.

The analog of the reference's fixture helpers
(pkg/scheduler/algorithm/predicates/testing_helper.go, testing/fake_lister.go,
test/utils/runners.go node/pod strategies).  Memory values are Mi-granular so
float32 device math stays exact for score parity.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.api.factory import (  # noqa: F401 (re-export)
    HOSTNAME_KEY as _FACTORY_HOSTNAME_KEY,
    make_node,
    make_pod,
)
from kubernetes_tpu.codec.schema import PadDims

# One shared pad configuration for the whole test-suite: identical tensor
# shapes => one XLA compilation serves every test (compiles dominate CPU test
# wall-clock otherwise).
TEST_DIMS = PadDims(N=16, B=16, TP=32)

ZONE_KEY = "failure-domain.beta.kubernetes.io/zone"
REGION_KEY = "failure-domain.beta.kubernetes.io/region"
HOSTNAME_KEY = "kubernetes.io/hostname"


_LABEL_KEYS = ["disk", "gpu", "tier", "arch"]
_LABEL_VALS = ["a", "b", "c"]
_TAINT_KEYS = ["dedicated", "gpu-node"]
_EFFECTS = ["NoSchedule", "PreferNoSchedule", "NoExecute"]


def random_cluster(
    rng: np.random.Generator,
    n_nodes: int = 12,
    n_pods: int = 30,
    zones: int = 3,
    with_affinity: bool = True,
) -> Tuple[List[Node], List[Pod], List[Tuple[str, Dict[str, str]]]]:
    nodes = []
    for i in range(n_nodes):
        labels = {
            ZONE_KEY: f"zone-{i % zones}",
            REGION_KEY: f"region-{i % 2}",
        }
        for k in _LABEL_KEYS:
            if rng.random() < 0.5:
                labels[k] = str(rng.choice(_LABEL_VALS))
        taints = []
        if rng.random() < 0.25:
            taints.append(
                {
                    "key": str(rng.choice(_TAINT_KEYS)),
                    "value": str(rng.choice(_LABEL_VALS)),
                    "effect": str(rng.choice(_EFFECTS)),
                }
            )
        images = []
        if rng.random() < 0.4:
            images.append(
                {
                    "names": [f"img-{rng.integers(4)}"],
                    "sizeBytes": int(rng.integers(1, 40)) * 64 * 1024 * 1024,
                }
            )
        nodes.append(
            make_node(
                f"node-{i}",
                cpu=str(int(rng.integers(2, 9))),
                mem=f"{int(rng.integers(2, 17))}Gi",
                pods=int(rng.integers(8, 32)),
                labels=labels,
                taints=taints,
                unschedulable=bool(rng.random() < 0.05),
                images=images,
            )
        )
    pods = []
    for i in range(n_pods):
        labels = {"app": f"app-{rng.integers(4)}"}
        affinity = None
        if with_affinity and rng.random() < 0.3:
            term = {
                "labelSelector": {"matchLabels": {"app": f"app-{rng.integers(4)}"}},
                "topologyKey": ZONE_KEY if rng.random() < 0.5 else HOSTNAME_KEY,
            }
            if rng.random() < 0.5:
                affinity = {"podAntiAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [term]}}
            else:
                affinity = {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution": [term]}}
        pods.append(
            make_pod(
                f"pod-{i}",
                cpu=f"{int(rng.integers(1, 9)) * 100}m" if rng.random() < 0.8 else None,
                mem=f"{int(rng.integers(1, 9)) * 128}Mi" if rng.random() < 0.8 else None,
                labels=labels,
                node_name=f"node-{rng.integers(n_nodes)}",
                ports=[{"hostPort": int(rng.integers(8000, 8004)), "protocol": "TCP"}]
                if rng.random() < 0.2
                else [],
                affinity=affinity,
                images=[f"img-{rng.integers(4)}"] if rng.random() < 0.3 else (),
            )
        )
    services = [
        ("default", {"app": f"app-{i}"}) for i in range(3)
    ]
    return nodes, pods, services


def random_pending_pod(rng: np.random.Generator, idx: int = 0, with_affinity: bool = True) -> Pod:
    labels = {"app": f"app-{rng.integers(4)}"}
    affinity: Optional[dict] = None
    r = rng.random()
    if with_affinity and r < 0.5:
        term = {
            "labelSelector": {"matchLabels": {"app": f"app-{rng.integers(4)}"}},
            "topologyKey": ZONE_KEY if rng.random() < 0.5 else HOSTNAME_KEY,
        }
        kind = "podAffinity" if rng.random() < 0.5 else "podAntiAffinity"
        if rng.random() < 0.5:
            affinity = {kind: {"requiredDuringSchedulingIgnoredDuringExecution": [term]}}
        else:
            affinity = {
                kind: {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {"weight": int(rng.integers(1, 100)), "podAffinityTerm": term}
                    ]
                }
            }
    node_affinity = None
    if rng.random() < 0.4:
        node_affinity = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {
                            "matchExpressions": [
                                {
                                    "key": str(rng.choice(_LABEL_KEYS)),
                                    "operator": str(rng.choice(["In", "NotIn", "Exists", "DoesNotExist"])),
                                    "values": [str(rng.choice(_LABEL_VALS))],
                                }
                            ]
                        }
                    ]
                },
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "weight": int(rng.integers(1, 100)),
                        "preference": {
                            "matchExpressions": [
                                {
                                    "key": str(rng.choice(_LABEL_KEYS)),
                                    "operator": "In",
                                    "values": [str(rng.choice(_LABEL_VALS))],
                                }
                            ]
                        },
                    }
                ],
            }
        }
    if affinity and node_affinity:
        affinity.update(node_affinity)
    elif node_affinity:
        affinity = node_affinity
    tolerations = []
    if rng.random() < 0.4:
        tolerations.append(
            {
                "key": str(rng.choice(_TAINT_KEYS)),
                "operator": "Exists" if rng.random() < 0.5 else "Equal",
                "value": str(rng.choice(_LABEL_VALS)),
                "effect": str(rng.choice(_EFFECTS + [""])),
            }
        )
    return make_pod(
        f"pending-{idx}",
        cpu=f"{int(rng.integers(1, 9)) * 100}m" if rng.random() < 0.8 else None,
        mem=f"{int(rng.integers(1, 9)) * 128}Mi" if rng.random() < 0.8 else None,
        labels=labels,
        node_selector={str(rng.choice(_LABEL_KEYS)): str(rng.choice(_LABEL_VALS))}
        if rng.random() < 0.3
        else None,
        tolerations=tolerations,
        affinity=affinity,
        ports=[{"hostPort": int(rng.integers(8000, 8004)), "protocol": "TCP"}]
        if rng.random() < 0.25
        else [],
        images=[f"img-{rng.integers(4)}"] if rng.random() < 0.4 else (),
    )
