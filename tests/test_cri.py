"""CRI over a real process boundary (runtime/cri.py): wire round trips,
the container state machine, the kubelet driving a runtime daemon in a
SEPARATE OS process, and kill -9 surfacing as pod failures — VERDICT r3
#5 'done' criteria.

Reference: pkg/kubelet/remote/remote_runtime.go:1-512,
cri-api/pkg/apis/runtime/v1alpha2/api.proto."""

import os
import signal
import subprocess
import sys
import time

import pytest

from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.cri import (
    CONTAINER_CREATED,
    CONTAINER_EXITED,
    CONTAINER_RUNNING,
    CRIError,
    CRIServer,
    CRIService,
    RemoteRuntime,
    RuntimeUnavailable,
)
from kubernetes_tpu.runtime.kubelet import FakeRuntime, Kubelet

from fixtures import make_node, make_pod


def _sock(tmp_path):
    return str(tmp_path / "cri.sock")


def test_wire_round_trip_and_container_lifecycle(tmp_path):
    srv = CRIServer(CRIService(FakeRuntime()), _sock(tmp_path)).start()
    rt = RemoteRuntime(_sock(tmp_path))
    try:
        assert rt.version()["runtime_api_version"] == "v1alpha2"
        assert all(c["status"] for c in rt.status()["conditions"])
        sid = rt.run_pod_sandbox(make_pod("web"))
        assert [sb["id"] for sb in rt.list_pod_sandboxes()] == [sid]
        assert rt.pod_sandbox_status(sid)["pod"] == ["default", "web"]
        # container state machine: CREATED -> RUNNING -> EXITED
        cid = rt.create_container(sid, "app", image="nginx")
        assert rt.container_status(cid)["state"] == CONTAINER_CREATED
        rt.start_container(cid)
        assert rt.container_status(cid)["state"] == CONTAINER_RUNNING
        with pytest.raises(CRIError):
            rt.start_container(cid)  # double-start
        with pytest.raises(CRIError):
            rt.remove_container(cid)  # running
        # stopping the sandbox kills its containers (exit 137)
        rt.stop_pod_sandbox(sid)
        st = rt.container_status(cid)
        assert st["state"] == CONTAINER_EXITED and st["exit_code"] == 137
        rt.remove_pod_sandbox(sid)
        assert rt.list_containers() == []
        with pytest.raises(CRIError):
            rt.container_status(cid)
    finally:
        rt.close()
        srv.stop()


def test_unknown_method_and_missing_sandbox(tmp_path):
    srv = CRIServer(CRIService(FakeRuntime()), _sock(tmp_path)).start()
    rt = RemoteRuntime(_sock(tmp_path))
    try:
        with pytest.raises(CRIError):
            rt._call("no_such_verb")
        with pytest.raises(CRIError):
            rt.create_container("sandbox-404", "app")
    finally:
        rt.close()
        srv.stop()


def _spawn_runtime_daemon(sock_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubernetes_tpu.runtime.cri",
         "--socket", sock_path, "--backend", "fake"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    deadline = time.time() + 20
    while time.time() < deadline:
        if os.path.exists(sock_path):
            return proc
        if proc.poll() is not None:
            raise RuntimeError(
                f"runtime daemon died: {proc.stdout.read().decode()}")
        time.sleep(0.05)
    proc.kill()
    raise RuntimeError("runtime daemon never bound its socket")


def test_kubelet_against_separate_process_runtime(tmp_path):
    """The kubelet syncs pods through a runtime living in ANOTHER OS
    process; kill -9 of that process surfaces as pod sync failures and
    events — the kubelet keeps running."""
    sock_path = _sock(tmp_path)
    daemon = _spawn_runtime_daemon(sock_path)
    cluster = LocalCluster()
    node = make_node("n1", cpu="4", mem="8Gi")
    rt = RemoteRuntime(sock_path, timeout=3.0)
    kubelet = Kubelet(cluster, node, runtime=rt)
    try:
        pod = make_pod("web", cpu="100m", node_name="n1")
        cluster.add_pod(pod)
        kubelet.sync_pod(cluster.get("pods", "default", "web"))
        got = cluster.get("pods", "default", "web")
        assert got.status.phase == "Running"
        assert rt.list_pod_sandboxes()[0]["pod"] == ("default", "web")
        # the runtime process dies hard
        daemon.send_signal(signal.SIGKILL)
        daemon.wait(timeout=10)
        # a fresh pod syncs WITHOUT crashing the kubelet: pod failure only
        pod2 = make_pod("web2", cpu="100m", node_name="n1")
        cluster.add_pod(pod2)
        kubelet.sync_pod(cluster.get("pods", "default", "web2"))
        got2 = cluster.get("pods", "default", "web2")
        assert got2.status.phase != "Running"
        events = cluster.events.events(reason="FailedCreatePodSandBox")
        assert events, "runtime failure must surface as a pod event"
        # PLEG sweeps degrade gracefully too
        assert kubelet.pleg_relist() == 0
        # direct client calls raise the typed transport error
        with pytest.raises(RuntimeUnavailable):
            rt.list_pod_sandboxes()
    finally:
        rt.close()
        if daemon.poll() is None:
            daemon.kill()


def test_process_runtime_behind_cri_daemon(tmp_path):
    """ProcessRuntime (real pause processes) served over the socket from
    a separate daemon process: the sandbox is anchored by a live pause
    pid in THAT process tree."""
    sock_path = _sock(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    daemon = subprocess.Popen(
        [sys.executable, "-m", "kubernetes_tpu.runtime.cri",
         "--socket", sock_path, "--backend", "process"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        deadline = time.time() + 30
        while not os.path.exists(sock_path):
            if daemon.poll() is not None:
                pytest.skip("pause build unavailable: "
                            + daemon.stdout.read().decode()[:200])
            if time.time() > deadline:
                raise RuntimeError("daemon never bound socket")
            time.sleep(0.05)
        rt = RemoteRuntime(sock_path, timeout=5.0)
        sid = rt.run_pod_sandbox(make_pod("anchored"))
        sb = rt.pod_sandbox_status(sid)
        pid = sb.get("pid")
        assert pid and pid != os.getpid()
        os.kill(pid, 0)  # alive
        rt.stop_pod_sandbox(sid)
        rt.remove_pod_sandbox(sid)
        assert rt.list_pod_sandboxes() == []
        rt.close()
    finally:
        if daemon.poll() is None:
            daemon.kill()
            daemon.wait(timeout=5)


def test_exec_sync_runs_in_container_context(tmp_path):
    """ExecSync (api.proto): command output + exit codes round-trip the
    socket; non-running containers refuse."""
    srv = CRIServer(CRIService(FakeRuntime()), _sock(tmp_path)).start()
    rt = RemoteRuntime(_sock(tmp_path))
    try:
        sid = rt.run_pod_sandbox(make_pod("web"))
        cid = rt.create_container(sid, "app")
        with pytest.raises(CRIError):
            rt.exec_sync(cid, ["true"])  # CREATED, not RUNNING
        rt.start_container(cid)
        out = rt.exec_sync(cid, ["echo", "hello from exec"])
        assert out["exit_code"] == 0
        assert "hello from exec" in out["stdout"]
        out = rt.exec_sync(cid, ["sh", "-c", "echo oops >&2; exit 3"])
        assert out["exit_code"] == 3 and "oops" in out["stderr"]
        out = rt.exec_sync(cid, ["/no/such/binary"])
        assert out["exit_code"] == 126
    finally:
        rt.close()
        srv.stop()


def test_kubelet_starts_spec_containers_over_cri(tmp_path):
    """kuberuntime SyncPod step 6-7: the kubelet creates + starts one CRI
    container per spec container inside the sandbox; teardown exits them
    with the sandbox."""
    srv = CRIServer(CRIService(FakeRuntime()), _sock(tmp_path)).start()
    rt = RemoteRuntime(_sock(tmp_path))
    cluster = LocalCluster()
    kubelet = Kubelet(cluster, make_node("n1", cpu="4", mem="8Gi"),
                      runtime=rt)
    try:
        pod = make_pod("web", node_name="n1", requests={"cpu": "100m"},
                       extra_containers=[{"cpu": "100m"}])
        cluster.add_pod(pod)
        kubelet.sync_pod(cluster.get("pods", "default", "web"))
        sid = kubelet.sandbox_of[("default", "web")]
        containers = rt.list_containers(sandbox_id=sid)
        assert len(containers) == 2
        assert all(c["state"] == CONTAINER_RUNNING for c in containers)
        kubelet._teardown(("default", "web"))
        assert rt.list_containers() == []
    finally:
        rt.close()
        srv.stop()
