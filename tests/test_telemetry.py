"""Cluster + device telemetry (ISSUE 8).

Pins the tentpole contracts: the jitted analytics kernel is BIT-EXACT
against the numpy reference on randomized snapshots (including recycled
rows and an empty cluster), the multi-window SLO burn-rate math on
synthetic histories, the slo_burn postmortem trigger + throttle (an
induced deadline-overrun storm fires exactly ONE), the /debug/cluster
endpoint's limit/cap behavior, the memory_stats CPU fallback, and the
heartbeat satellite.
"""

import dataclasses
import json
import logging
import time
import urllib.request

import numpy as np
import pytest

from kubernetes_tpu.ops.analytics import (
    OCC_BINS,
    RESOURCE_NAMES,
    STAT_NAMES,
    analytics_to_dict,
    cluster_analytics,
    cluster_analytics_np,
)
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.chaos import Disruptions
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.flightrecorder import FlightRecorder
from kubernetes_tpu.runtime.health import start_health_server
from kubernetes_tpu.runtime.queue import PodBackoff, PriorityQueue
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.runtime.telemetry import (
    SLOEvaluator,
    SLOObjective,
    TelemetryHub,
    device_memory_stats,
)
from kubernetes_tpu.utils import metrics as m

from fixtures import make_node, make_pod


def _mini_scheduler(recorder=None, nodes=1, **cfg_kw):
    cache = SchedulerCache()
    queue = PriorityQueue(backoff=PodBackoff(initial=0.01, max_duration=0.05))
    cfg = SchedulerConfig(disable_preemption=True, **cfg_kw)
    sched = Scheduler(
        cache=cache, queue=queue, binder=lambda p, n: True, config=cfg,
        flight_recorder=recorder,
    )
    for i in range(nodes):
        cache.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    return sched, queue


def _assert_bit_exact(alloc, req, valid):
    dev = cluster_analytics(alloc, req, valid)
    ref = cluster_analytics_np(alloc, req, valid)
    for f in dataclasses.fields(dev):
        a, b = np.asarray(getattr(dev, f.name)), np.asarray(
            getattr(ref, f.name)
        )
        assert np.array_equal(a, b), (
            f"{f.name} differs: kernel={a!r} reference={b!r}"
        )
    return ref


# ------------------------------------------------------- analytics kernel


def test_analytics_bit_exact_on_randomized_snapshots(rng):
    """Tentpole acceptance: the jitted kernel and the numpy reference
    agree to the BIT on randomized snapshots — overcommitted nodes,
    zero-capacity columns, invalid (recycled/padding) rows."""
    for trial in range(8):
        N = int(rng.choice([8, 16, 64, 257, 512, 1000]))
        R = 8
        alloc = (
            rng.uniform(0, 1e4, (N, R)) * rng.integers(0, 2, (N, R))
        ).astype(np.float32)
        req = (alloc * rng.uniform(0, 1.5, (N, R))).astype(np.float32)
        valid = rng.random(N) < 0.8
        _assert_bit_exact(alloc, req, valid)


def test_analytics_bit_exact_on_empty_cluster():
    N, R = 16, 8
    zeros = np.zeros((N, R), np.float32)
    ref = _assert_bit_exact(zeros, zeros, np.zeros(N, bool))
    d = analytics_to_dict(ref)
    assert d["nodes"] == 0
    assert d["fragmentation"] == 0.0
    assert d["utilization"]["cpu"]["p99"] == 0.0
    assert sum(d["occupancy"]) == 0


def test_analytics_bit_exact_on_encoder_snapshot_with_recycled_rows():
    """The real input shape: an encoder-built snapshot after node adds,
    pod commits, and a node REMOVAL (a recycled row the valid mask must
    exclude from every statistic)."""
    from kubernetes_tpu.codec import SnapshotEncoder

    enc = SnapshotEncoder()
    enc.add_nodes([
        make_node(f"n-{i}", cpu="8", mem="16Gi", pods=10) for i in range(6)
    ])
    for i in range(8):
        enc.add_pod(make_pod(f"p-{i}", cpu="1", mem="1Gi",
                             node_name=f"n-{i % 6}"))
    enc.remove_node("n-3")
    snap = enc.snapshot()
    ref = _assert_bit_exact(snap.allocatable, snap.requested, snap.valid)
    d = analytics_to_dict(ref)
    assert d["nodes"] == 5
    assert d["pods_running"] == pytest.approx(7.0)  # n-3's pod went too
    assert 0.0 < d["utilization"]["cpu"]["mean"] <= 1.0
    assert sum(d["occupancy"]) == 5


def test_analytics_semantics_known_cluster():
    """Hand-checked values on a 3-node cluster: utilization stats,
    stranded capacity, fragmentation, occupancy deciles."""
    R = 8
    alloc = np.zeros((4, R), np.float32)
    req = np.zeros((4, R), np.float32)
    valid = np.array([True, True, True, False])
    # node0: half cpu, memory EXHAUSTED -> its free cpu is stranded
    alloc[0, :4] = (4000, 8.0, 10.0, 10)
    req[0, :4] = (2000, 8.0, 0.0, 5)
    # node1: cpu exhausted, half memory -> its free memory is stranded
    alloc[1, :4] = (4000, 8.0, 10.0, 10)
    req[1, :4] = (4000, 4.0, 0.0, 9)
    # node2: idle
    alloc[2, :4] = (2000, 4.0, 10.0, 10)
    # node3 is INVALID and fully loaded — must not count anywhere
    alloc[3, :4] = (1000, 1.0, 1.0, 1)
    req[3, :4] = (1000, 1.0, 1.0, 1)
    d = analytics_to_dict(_assert_bit_exact(alloc, req, valid))
    assert d["nodes"] == 3
    assert d["utilization"]["cpu"]["mean"] == pytest.approx((0.5 + 1.0) / 3)
    assert d["utilization"]["cpu"]["max"] == 1.0
    assert d["utilization"]["memory"]["p50"] == pytest.approx(0.5)
    assert d["stranded"]["cpu"] == pytest.approx(2000.0)   # node0's free cpu
    assert d["stranded"]["memory"] == pytest.approx(4.0)   # node1's free mem
    # free cpu total = 2000 + 0 + 2000; free mem total = 0 + 4 + 4
    assert d["fragmentation"] == pytest.approx(
        0.5 * (2000.0 / 4000.0) + 0.5 * (4.0 / 8.0)
    )
    assert d["largest_free"]["cpu"] == pytest.approx(2000.0)
    # occupancy: 50% -> decile 5, 90% -> decile 9, 0% -> decile 0
    occ = d["occupancy"]
    assert occ[0] == 1 and occ[5] == 1 and occ[9] == 1
    assert sum(occ) == 3
    assert d["pods_running"] == pytest.approx(14.0)
    assert d["imbalance"] > 0.0


def test_analytics_dict_shape():
    N, R = 8, 8
    d = analytics_to_dict(cluster_analytics_np(
        np.ones((N, R), np.float32), np.zeros((N, R), np.float32),
        np.ones(N, bool),
    ))
    assert set(d["utilization"]) == set(RESOURCE_NAMES)
    for res in RESOURCE_NAMES:
        assert set(d["utilization"][res]) == set(STAT_NAMES)
    assert len(d["occupancy"]) == OCC_BINS
    json.dumps(d)  # the /debug/cluster body must serialize


# ------------------------------------------------------------ SLO windows


def test_slo_burn_window_math_synthetic_history():
    """Window math on a synthetic clock: burn = bad fraction within the
    window / error budget, per window."""
    clk = [100.0]
    ev = SLOEvaluator(
        (SLOObjective("o", objective=0.9, fast_window_s=10.0,
                      slow_window_s=100.0),),
        clock=lambda: clk[0],
    )
    # t=100: 8 good, 2 bad -> bad frac 0.2, budget 0.1 -> burn 2.0
    ev.observe("o", good=8, bad=2)
    fast, slow = ev.burn_rates("o")
    assert fast == pytest.approx(2.0) and slow == pytest.approx(2.0)
    # 20s later the events left the fast window but not the slow one
    clk[0] = 120.0
    ev.observe("o", good=10, bad=0)
    fast, slow = ev.burn_rates("o")
    assert fast == pytest.approx(0.0)
    assert slow == pytest.approx((2 / 20) / 0.1)
    # past the slow window everything ages out
    clk[0] = 250.0
    ev.observe("o", good=1, bad=0)
    fast, slow = ev.burn_rates("o")
    assert fast == 0.0 and slow == 0.0
    # unknown objectives are ignored, not an error
    ev.observe("nope", bad=1)


def test_slo_alert_needs_both_windows_and_rearms():
    clk = [0.0]
    ev = SLOEvaluator(
        (SLOObjective("o", objective=0.9, fast_window_s=10.0,
                      slow_window_s=1000.0, burn_threshold=1.0),),
        clock=lambda: clk[0],
    )
    # slow window poisoned by old badness, fast window clean -> no alert
    ev.observe("o", bad=5)
    clk[0] = 500.0
    ev.observe("o", good=50)
    assert ev.evaluate() == []
    # now the fast window burns too -> exactly one alert...
    ev.observe("o", bad=50)
    fired = ev.evaluate()
    assert [f[0] for f in fired] == ["o"]
    # ...and a still-burning followup does NOT re-fire (hysteresis)
    ev.observe("o", bad=5)
    assert ev.evaluate() == []
    # fast recovery re-arms; a fresh burn fires again
    clk[0] = 600.0
    ev.observe("o", good=100)
    assert ev.evaluate() == []
    ev.observe("o", bad=1000)
    assert [f[0] for f in ev.evaluate()] == ["o"]
    assert ev.alerts_total == 2
    assert m.SLO_BURN_RATE.value(objective="o", window="fast") > 1.0


@pytest.mark.chaos
def test_deadline_overrun_storm_fires_one_throttled_slo_burn_postmortem():
    """Acceptance: an induced deadline-overrun storm fires exactly ONE
    throttled slo_burn postmortem, and the /metrics burn-rate gauge for
    the cycle_deadline objective crosses 1.0."""
    fr = FlightRecorder(postmortem_min_interval_s=60.0)
    sched, queue = _mini_scheduler(
        recorder=fr,
        cycle_deadline_s=1e-9,  # every non-empty cycle overruns
        adaptive_batch=True, batch_size_min=1, batch_size=4,
    )
    for i in range(12):
        queue.add(make_pod(f"storm-{i}", cpu="10m"))
    deadline = time.monotonic() + 30
    while queue.has_schedulable() and time.monotonic() < deadline:
        sched.run_once(timeout=0.0)
    assert sched.telemetry is not None
    pms = fr.postmortems(trigger="slo_burn")
    assert len(pms) == 1, (
        f"expected exactly one throttled slo_burn postmortem, got "
        f"{[p['detail'] for p in pms]}"
    )
    assert "cycle_deadline" in pms[0]["detail"]
    fast = m.SLO_BURN_RATE.value(objective="cycle_deadline", window="fast")
    slow = m.SLO_BURN_RATE.value(objective="cycle_deadline", window="slow")
    assert fast >= 1.0 and slow >= 1.0
    assert m.SLO_ALERTS.value(objective="cycle_deadline") >= 1


# ----------------------------------------------------------- the live hub


def test_scheduler_telemetry_samples_and_gauges():
    sched, queue = _mini_scheduler(nodes=2)
    for i in range(4):
        queue.add(make_pod(f"p{i}", cpu="500m"))
    sched.run_once(timeout=0.2)
    queue.add(make_pod("late", cpu="500m"))
    sched.run_once(timeout=0.2)
    hub = sched.telemetry
    s = hub.summary()
    assert s["samples"] >= 1 and s["cycles"] >= 2
    a = s["analytics"]
    assert a["nodes"] == 2
    # the sample reflects the SNAPSHOT its cycle dispatched against
    # (one-cycle lag): by cycle 2 the first batch's pods are visible
    assert a["utilization"]["cpu"]["mean"] > 0.0
    assert 0.0 <= a["fragmentation"] <= 1.0
    assert m.CLUSTER_NODES.value == 2.0
    assert m.CLUSTER_UTILIZATION.value(resource="cpu", stat="mean") > 0.0
    assert m.PENDING_PRESSURE.value(tier="bulk") == 0.0
    # the sample source is the device-resident path on a healthy engine
    assert hub.debug_payload()["samples"][-1]["source"] == "device"
    # launch EWMA recorded for the dispatched width
    assert hub._launch_ewma, "no launch EWMA recorded"


def test_telemetry_interval_cycles_amortizes_sampling():
    sched, queue = _mini_scheduler(telemetry_interval_cycles=3)
    for i in range(6):
        queue.add(make_pod(f"p{i}", cpu="10m"))
        sched.run_once(timeout=0.2)
    hub = sched.telemetry
    hub.summary()
    # 6 cycles at interval 3 -> 2 dispatches, ~1-2 materialized samples
    assert hub.cycles_total >= 6
    assert 1 <= hub.samples_total <= 2


@pytest.mark.chaos
def test_degraded_cycle_falls_back_to_host_analytics():
    """Breaker open -> resident device buffers are invalidated; the
    telemetry stream must continue through the numpy reference."""
    sched, queue = _mini_scheduler(
        device_retry_max=0, breaker_failure_threshold=1,
        breaker_open_s=10.0, cpu_fallback=True,
    )
    dis = Disruptions(LocalCluster())
    dis.device_lost()
    try:
        queue.add(make_pod("degraded", cpu="100m"))
        sched.run_once(timeout=0.2)
        queue.add(make_pod("degraded-2", cpu="100m"))
        sched.run_once(timeout=0.2)
    finally:
        dis.clear_device_faults()
    assert sched.device_health.state == "open"
    payload = sched.telemetry.debug_payload()
    assert payload["samples"], "telemetry stream died with the device"
    assert payload["samples"][-1]["source"] == "host"


def test_telemetry_off_removes_the_hook():
    sched, queue = _mini_scheduler(telemetry=False)
    assert sched.telemetry is None
    queue.add(make_pod("p", cpu="100m"))
    sched.run_once(timeout=0.2)  # must not crash without the hub


# --------------------------------------------------- device runtime facts


def test_memory_stats_fallback_on_cpu():
    """XLA:CPU devices return None from memory_stats(): the helper must
    yield {} without raising, and set no HBM gauges."""
    import jax

    out = device_memory_stats()
    if jax.default_backend() == "cpu":
        assert out == {}
    # whatever the backend, the gauge family must still expose cleanly
    assert "ktpu_device_hbm_bytes" in m.DEVICE_HBM.expose()


def test_launch_ewma_and_prune():
    hub = TelemetryHub()
    hub.note_launch(256, 0.010)
    first = hub._launch_ewma[256]
    assert first == pytest.approx(0.010)
    hub.note_launch(256, 0.020)
    assert 0.010 < hub._launch_ewma[256] < 0.020
    hub.note_launch(512, 0.030)
    assert m.LAUNCH_EWMA.value(width="512") == pytest.approx(0.030)
    hub.prune_widths({256})
    assert 512 not in hub._launch_ewma
    assert m.LAUNCH_EWMA.value(width="512") == 0.0
    assert m.LAUNCH_EWMA.value(width="256") > 0.0


# ------------------------------------------------------- /debug/cluster


def test_debug_cluster_endpoint_on_health_server_with_limit():
    sched, queue = _mini_scheduler()
    for i in range(3):
        queue.add(make_pod(f"p{i}", cpu="100m"))
        sched.run_once(timeout=0.2)
    sched.telemetry.summary()  # drain the in-flight sample
    srv = start_health_server()
    try:
        h, p = srv.address
        with urllib.request.urlopen(
            f"http://{h}:{p}/debug/cluster", timeout=5
        ) as r:
            assert r.headers["Content-Type"] == "application/json"
            body = json.loads(r.read())
        with urllib.request.urlopen(
            f"http://{h}:{p}/debug/cluster?limit=1", timeout=5
        ) as r:
            limited = json.loads(r.read())
    finally:
        srv.stop()
    assert body["samples"] and body["summary"]["analytics"]["nodes"] == 1
    assert len(limited["samples"]) == 1
    assert limited["samples"][0] == body["samples"][-1]  # newest kept


def test_debug_cluster_endpoint_on_apiserver_inflight_exempt():
    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.apiserver.fairness import FlowControlConfig

    sched, queue = _mini_scheduler()
    queue.add(make_pod("p", cpu="100m"))
    sched.run_once(timeout=0.2)
    sched.telemetry.summary()
    # a zero-inflight limiter rejects EVERY non-exempt request: the
    # debug endpoint must still answer (diagnosing an overload needs it)
    srv = APIServer(
        cluster=LocalCluster(),
        flow_control=FlowControlConfig(
            max_inflight_readonly=1, max_inflight_mutating=1,
            queue_length_per_flow=0, queue_wait_timeout_s=0.01,
        ),
    ).start()
    try:
        with urllib.request.urlopen(
            f"{srv.url}/debug/cluster?limit=2", timeout=5
        ) as r:
            body = json.loads(r.read())
    finally:
        srv.stop()
    assert "summary" in body and "samples" in body


def test_debug_cluster_body_respects_response_cap():
    """The shared bounded_json halving: a tiny cap forces the sample
    list down (well-formed JSON either way)."""
    from kubernetes_tpu.runtime.ledger import debug_body

    hub = TelemetryHub(ring_capacity=64)
    N, R = 8, 8
    alloc = np.ones((N, R), np.float32)
    req = np.zeros((N, R), np.float32)
    valid = np.ones(N, bool)
    for c in range(40):
        hub.on_cycle(cycle=c, tier="bulk", cycle_s=0.01, placed=1,
                     unschedulable=0, host_snapshot=(alloc, req, valid))
    hub.summary()
    full = json.loads(debug_body(hub.debug_payload, ""))
    assert len(full["samples"]) >= 30
    capped = json.loads(debug_body(hub.debug_payload, "", cap=8192))
    assert len(capped["samples"]) < len(full["samples"])


# ------------------------------------------------------------- heartbeat


def test_heartbeat_line_emitted_and_off_when_zero():
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("kubernetes_tpu")
    handler = _Capture(level=logging.INFO)
    old_level = logger.level
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        sched, queue = _mini_scheduler(heartbeat_s=0.01)
        queue.add(make_pod("hb", cpu="100m"))
        sched.run_once(timeout=0.2)
        time.sleep(0.02)
        sched.run_once(timeout=0.0)  # idle poll must still heartbeat
        beats = [r for r in records if r.startswith("heartbeat:")]
        assert beats, "no heartbeat line on a quiet loop"
        line = beats[-1]
        for field in ("cycles=", "placed=", "unschedulable=", "active=",
                      "express=", "breaker=", "batch=", "hbm_bytes="):
            assert field in line, f"heartbeat line missing {field}: {line}"
        assert "placed=1" in line
        assert "breaker=closed" in line

        # off when 0 (the default): no heartbeat however long we wait
        records.clear()
        sched2, queue2 = _mini_scheduler()
        queue2.add(make_pod("quiet", cpu="100m"))
        sched2.run_once(timeout=0.2)
        time.sleep(0.02)
        sched2.run_once(timeout=0.0)
        assert not [r for r in records if r.startswith("heartbeat:")]
    finally:
        logger.removeHandler(handler)
        logger.setLevel(old_level)


# -------------------------------------------------------------- /metrics


def test_telemetry_families_survive_strict_metrics_parser():
    from test_metrics_format import check_histograms, parse_exposition

    sched, queue = _mini_scheduler()
    queue.add(make_pod("p", cpu="100m"))
    sched.run_once(timeout=0.2)
    sched.telemetry.summary()
    families = parse_exposition(m.REGISTRY.expose())
    check_histograms(families)
    for fam in (
        "scheduler_cluster_utilization_ratio",
        "scheduler_cluster_fragmentation_index",
        "scheduler_cluster_dominant_share_stddev",
        "scheduler_cluster_pods_per_node_occupancy_nodes",
        "scheduler_pending_pressure_pods",
        "scheduler_launch_duration_ewma_seconds",
        "scheduler_slo_burn_rate",
        "scheduler_telemetry_seconds_total",
        "ktpu_device_hbm_bytes",
        "ktpu_compile_cache_events_total",
        "ktpu_backend_compile_seconds_total",
    ):
        assert fam in families, f"{fam} missing from /metrics"
    util = [
        (lbl, v) for _, lbl, v in
        families["scheduler_cluster_utilization_ratio"]["samples"]
    ]
    assert len(util) == 20  # 4 resources x 5 stats
    for lbl, v in util:
        assert 0.0 <= v <= 1.0 or lbl["resource"] == "ephemeral"
