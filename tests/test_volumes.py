"""Volume predicates: zone conflict, binding, PVC-resolved count limits —
device kernels vs the object-level golden."""

import numpy as np
import pytest

from kubernetes_tpu.api.storage import PersistentVolume, PersistentVolumeClaim, StorageClass
from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.schema import FilterConfig, PRED_INDEX
from kubernetes_tpu.cpuref import CPUScheduler
from kubernetes_tpu.ops import filter_batch

from fixtures import TEST_DIMS, make_node, make_pod

ZONE = "failure-domain.beta.kubernetes.io/zone"


def pvc_pod(name, claim, **kw):
    return make_pod(name, volumes=[{"persistentVolumeClaim": {"claimName": claim}}], **kw)


def build(nodes, pods, pvs, pvcs, scs=()):
    enc = SnapshotEncoder(TEST_DIMS)
    for n in nodes:
        enc.add_node(n)
    for sc in scs:
        enc.add_storage_class(sc)
    for pv in pvs:
        enc.add_pv(pv)
    for c in pvcs:
        enc.add_pvc(c)
    for p in pods:
        enc.add_pod(p)
    return enc


def check(enc, nodes, pods, pvs, pvcs, scs, pending, preds):
    golden = CPUScheduler(nodes, pods, pvs=pvs, pvcs=pvcs, storage_classes=scs)
    batch = enc.encode_pods(pending)
    _, per_pred = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
    per_pred = np.asarray(per_pred)
    for b, pod in enumerate(pending):
        want = golden.predicates(pod, nodes[0])  # warm path; per-node below
        for node in nodes:
            gold = golden.predicates(pod, node)
            row = enc.node_rows[node.name]
            for pname in preds:
                got = bool(per_pred[b, PRED_INDEX[pname], row])
                assert got == gold[pname], (pod.name, node.name, pname, got, gold[pname])


def test_zone_conflict_bound_pv():
    nodes = [make_node("a", labels={ZONE: "z1"}), make_node("b", labels={ZONE: "z2"})]
    pv = PersistentVolume.from_dict({
        "metadata": {"name": "pv1", "labels": {ZONE: "z1"}},
        "spec": {"gcePersistentDisk": {"pdName": "d"}, "capacity": {"storage": "10Gi"}},
    })
    pvc = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "c1", "namespace": "default"},
        "spec": {"volumeName": "pv1"},
    })
    enc = build(nodes, [], [pv], [pvc])
    pending = [pvc_pod("p", "c1")]
    check(enc, nodes, [], [pv], [pvc], [], pending,
          ["NoVolumeZoneConflict", "CheckVolumeBinding"])
    batch = enc.encode_pods(pending)
    mask, _ = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
    mask = np.asarray(mask)[0]
    assert mask[enc.node_rows["a"]] and not mask[enc.node_rows["b"]]


def test_multi_zone_pv_label():
    nodes = [make_node(f"n{i}", labels={ZONE: f"z{i}"}) for i in range(3)]
    pv = PersistentVolume.from_dict({
        "metadata": {"name": "pv1", "labels": {ZONE: "z0__z2"}},
        "spec": {"awsElasticBlockStore": {"volumeID": "v"}},
    })
    pvc = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "c1", "namespace": "default"},
        "spec": {"volumeName": "pv1"},
    })
    enc = build(nodes, [], [pv], [pvc])
    pending = [pvc_pod("p", "c1")]
    check(enc, nodes, [], [pv], [pvc], [], pending, ["NoVolumeZoneConflict"])


def test_local_pv_node_affinity():
    nodes = [make_node("a"), make_node("b")]
    pv = PersistentVolume.from_dict({
        "metadata": {"name": "local1"},
        "spec": {
            "capacity": {"storage": "50Gi"},
            "storageClassName": "local",
            "nodeAffinity": {"required": {"nodeSelectorTerms": [
                {"matchExpressions": [
                    {"key": "kubernetes.io/hostname", "operator": "In", "values": ["a"]}
                ]}
            ]}},
        },
    })
    pvc = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "c1", "namespace": "default"},
        "spec": {"volumeName": "local1", "storageClassName": "local"},
    })
    enc = build(nodes, [], [pv], [pvc])
    pending = [pvc_pod("p", "c1")]
    check(enc, nodes, [], [pv], [pvc], [], pending, ["CheckVolumeBinding"])
    batch = enc.encode_pods(pending)
    mask, _ = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
    mask = np.asarray(mask)[0]
    assert mask[enc.node_rows["a"]] and not mask[enc.node_rows["b"]]


def test_unbound_claim_with_candidates():
    nodes = [make_node("a", labels={ZONE: "z1"}), make_node("b", labels={ZONE: "z2"})]
    pv = PersistentVolume.from_dict({
        "metadata": {"name": "avail", "labels": {ZONE: "z2"}},
        "spec": {"capacity": {"storage": "100Gi"}, "storageClassName": "std",
                 "accessModes": ["ReadWriteOnce"]},
    })
    pvc = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "want", "namespace": "default"},
        "spec": {"storageClassName": "std",
                 "resources": {"requests": {"storage": "10Gi"}},
                 "accessModes": ["ReadWriteOnce"]},
    })
    enc = build(nodes, [], [pv], [pvc])
    pending = [pvc_pod("p", "want")]
    check(enc, nodes, [], [pv], [pvc], [], pending, ["CheckVolumeBinding"])
    # too-big claim: no candidate, no provisioner -> fails everywhere
    big = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "big", "namespace": "default"},
        "spec": {"storageClassName": "std",
                 "resources": {"requests": {"storage": "1000Gi"}}},
    })
    enc.add_pvc(big)
    pending2 = [pvc_pod("p2", "big")]
    check(enc, nodes, [], [pv], [pvc, big], [], pending2, ["CheckVolumeBinding"])


def test_wait_for_first_consumer_provisioning():
    nodes = [make_node("a")]
    sc = StorageClass.from_dict({
        "metadata": {"name": "fast"}, "provisioner": "csi.example.com",
        "volumeBindingMode": "WaitForFirstConsumer",
    })
    pvc = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "dyn", "namespace": "default"},
        "spec": {"storageClassName": "fast",
                 "resources": {"requests": {"storage": "10Gi"}}},
    })
    enc = build(nodes, [], [], [pvc], [sc])
    pending = [pvc_pod("p", "dyn")]
    check(enc, nodes, [], [], [pvc], [sc], pending, ["CheckVolumeBinding"])


def test_missing_pvc_fails_everywhere():
    nodes = [make_node("a")]
    enc = build(nodes, [], [], [])
    pending = [pvc_pod("p", "ghost")]
    check(enc, nodes, [], [], [], [], pending, ["CheckVolumeBinding"])


def test_pvc_resolved_volume_limits():
    node = make_node("a")
    from kubernetes_tpu.api.resource import parse_quantity

    node.status.allocatable["attachable-volumes-aws-ebs"] = parse_quantity("1")
    nodes = [node, make_node("b")]
    pv1 = PersistentVolume.from_dict({
        "metadata": {"name": "ebs1"},
        "spec": {"awsElasticBlockStore": {"volumeID": "v1"}},
    })
    pv2 = PersistentVolume.from_dict({
        "metadata": {"name": "ebs2"},
        "spec": {"awsElasticBlockStore": {"volumeID": "v2"}},
    })
    c1 = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "c1", "namespace": "default"}, "spec": {"volumeName": "ebs1"}})
    c2 = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "c2", "namespace": "default"}, "spec": {"volumeName": "ebs2"}})
    existing = [pvc_pod("e1", "c1", node_name="a")]
    enc = build(nodes, existing, [pv1, pv2], [c1, c2])
    pending = [pvc_pod("p", "c2")]
    check(enc, nodes, existing, [pv1, pv2], [c1, c2], [], pending, ["MaxEBSVolumeCount"])
    batch = enc.encode_pods(pending)
    _, per_pred = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
    per = np.asarray(per_pred)[0, PRED_INDEX["MaxEBSVolumeCount"]]
    assert not per[enc.node_rows["a"]] and per[enc.node_rows["b"]]


def test_volume_binder_assume_and_revert():
    from kubernetes_tpu.runtime.volumebinder import VolumeBinder

    nodes = [make_node("a", labels={ZONE: "z1"}), make_node("b", labels={ZONE: "z2"})]
    pv = PersistentVolume.from_dict({
        "metadata": {"name": "avail", "labels": {ZONE: "z1"}},
        "spec": {"capacity": {"storage": "100Gi"}, "storageClassName": "std"},
    })
    pvc = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "want", "namespace": "default"},
        "spec": {"storageClassName": "std",
                 "resources": {"requests": {"storage": "10Gi"}}},
    })
    enc = build(nodes, [], [pv], [pvc])
    vb = VolumeBinder(enc)
    ok, assumptions = vb.assume_pod_volumes(pvc_pod("p", "want"), "b")
    assert not ok  # pv is zone-z1 only
    ok, assumptions = vb.assume_pod_volumes(pvc_pod("p", "want"), "a")
    assert ok and pvc.volume_name == "avail" and pv.phase == "Bound"
    vb.revert(assumptions)
    assert pvc.volume_name == "" and pv.phase == "Available"


def test_csi_per_driver_limits_and_counts():
    """MaxCSIVolumeCount accounts PER DRIVER (csi_volume_predicate.go):
    each driver's attachments count against its own
    attachable-volumes-csi-<driver> cap; different drivers don't share a
    budget."""
    node = make_node(
        "n1", cpu="8", mem="16Gi",
        allocatable_extra={"attachable-volumes-csi-driver-a": "1",
                           "attachable-volumes-csi-driver-b": "2"},
    )
    pvs = []
    pvcs = []
    for i, driver in enumerate(["driver-a", "driver-a", "driver-b"]):
        pvs.append(PersistentVolume.from_dict({
            "metadata": {"name": f"pv{i}"},
            "spec": {"capacity": {"storage": "1Gi"},
                     "accessModes": ["ReadWriteOnce"],
                     "csi": {"driver": driver, "volumeHandle": f"h{i}"}},
        }))
        pvcs.append(PersistentVolumeClaim.from_dict({
            "metadata": {"name": f"c{i}", "namespace": "default"},
            "spec": {"volumeName": f"pv{i}"},
        }))
    # two driver-a claims exceed its cap of 1; a+b together fit (separate
    # budgets); two driver-b claims fit its cap of 2
    over_a = make_pod("over-a", volumes=[
        {"persistentVolumeClaim": {"claimName": "c0"}},
        {"persistentVolumeClaim": {"claimName": "c1"}},
    ])
    mixed = make_pod("mixed", volumes=[
        {"persistentVolumeClaim": {"claimName": "c0"}},
        {"persistentVolumeClaim": {"claimName": "c2"}},
    ])
    enc = build([node], [], pvs, pvcs)
    golden = CPUScheduler([node], [], pvs=pvs, pvcs=pvcs)
    pending = [over_a, mixed]
    batch = enc.encode_pods(pending)
    _, per_pred = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
    row = PRED_INDEX["MaxCSIVolumeCount"]
    dev = np.asarray(per_pred)[:, row, 0]
    assert not dev[0], "two driver-a attachments must exceed cap 1"
    assert dev[1], "one a + one b ride separate budgets"
    # differential vs the golden
    for b, pod in enumerate(pending):
        assert golden.predicates(pod, node)["MaxCSIVolumeCount"] == bool(dev[b]), pod.name


def test_csi_driver_first_seen_at_encode_time():
    """A pending pod may introduce a CSI driver no assigned pod uses: the
    driver column must register BEFORE the batch arrays are cut (the
    extended-resource pre-registration discipline)."""
    enc = SnapshotEncoder(TEST_DIMS)
    node = make_node("n1", cpu="8", mem="16Gi")
    enc.add_node(node)
    pv = PersistentVolume.from_dict({
        "metadata": {"name": "pv0"},
        "spec": {"capacity": {"storage": "1Gi"},
                 "accessModes": ["ReadWriteOnce"],
                 "csi": {"driver": "fresh", "volumeHandle": "h"}},
    })
    pvc = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "c0", "namespace": "default"},
        "spec": {"volumeName": "pv0"},
    })
    enc.add_pv(pv)
    enc.add_pvc(pvc)
    pod = pvc_pod("p", "c0")
    batch = enc.encode_pods([pod])
    _, per_pred = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
    row = PRED_INDEX["MaxCSIVolumeCount"]
    assert bool(np.asarray(per_pred)[0, row, 0])
    golden = CPUScheduler([node], [], pvs=[pv], pvcs=[pvc])
    assert golden.predicates(pod, node)["MaxCSIVolumeCount"]


def test_unknown_driver_cap_does_not_clamp_generic_csi():
    """attachable-volumes-csi-<driver> for a driver with no volumes must
    constrain nothing (golden and device agree)."""
    node = make_node("n1", cpu="8", mem="16Gi",
                     allocatable_extra={"attachable-volumes-csi-rare": "1"})
    pvs, pvcs = [], []
    for i in range(2):  # two driverless CSI PVs (generic column)
        pvs.append(PersistentVolume.from_dict({
            "metadata": {"name": f"pv{i}"},
            "spec": {"capacity": {"storage": "1Gi"},
                     "accessModes": ["ReadWriteOnce"],
                     "csi": {"volumeHandle": f"h{i}"}},
        }))
        pvcs.append(PersistentVolumeClaim.from_dict({
            "metadata": {"name": f"c{i}", "namespace": "default"},
            "spec": {"volumeName": f"pv{i}"},
        }))
    pod = make_pod("p", volumes=[
        {"persistentVolumeClaim": {"claimName": "c0"}},
        {"persistentVolumeClaim": {"claimName": "c1"}},
    ])
    enc = build([node], [], pvs, pvcs)
    golden = CPUScheduler([node], [], pvs=pvs, pvcs=pvcs)
    batch = enc.encode_pods([pod])
    _, per_pred = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
    dev = bool(np.asarray(per_pred)[0, PRED_INDEX["MaxCSIVolumeCount"], 0])
    assert dev, "the rare-driver cap must not clamp the generic column"
    assert golden.predicates(pod, node)["MaxCSIVolumeCount"] == dev


def test_no_disk_conflict_read_only_allowance():
    """isVolumeConflict (predicates.go:295-328): GCE-PD mounts that are
    BOTH read-only coexist; any read-write side conflicts; EBS conflicts
    regardless of access mode."""
    node = make_node("n1", cpu="8", mem="16Gi")

    def gce(name, ro):
        return {"gcePersistentDisk": {"pdName": name, "readOnly": ro}}

    def ebs(name, ro):
        return {"awsElasticBlockStore": {"volumeID": name, "readOnly": ro}}

    cases = [
        # (existing volume, pending volume, fits?)
        (gce("d", True), gce("d", True), True),    # ro + ro: allowed
        (gce("d", True), gce("d", False), False),  # rw against ro mount
        (gce("d", False), gce("d", True), False),  # ro against rw mount
        (gce("d", False), gce("d", False), False),
        (ebs("e", True), ebs("e", True), False),   # EBS: no allowance
        (gce("d", True), gce("other", False), True),
    ]
    for i, (existing_vol, pending_vol, fits) in enumerate(cases):
        existing = make_pod(f"e{i}", cpu="10m", mem="1Mi", node_name="n1",
                            volumes=[existing_vol])
        pending = make_pod(f"p{i}", cpu="10m", mem="1Mi",
                           volumes=[pending_vol])
        enc = build([node], [existing], [], [])
        golden = CPUScheduler([node], [existing])
        batch = enc.encode_pods([pending])
        _, per_pred = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
        dev = bool(np.asarray(per_pred)[0, PRED_INDEX["NoDiskConflict"], 0])
        assert dev == fits, (i, existing_vol, pending_vol, dev)
        assert golden.predicates(pending, node)["NoDiskConflict"] == fits, i


def test_disk_conflict_iscsi_iqn_and_rbd_monitor_overlap():
    """isVolumeConflict identity rules (predicates.go:253-272): ISCSI keys
    on IQN alone (multi-path portals still conflict); RBD keys on monitor
    OVERLAP + pool + image."""
    node = make_node("n1", cpu="8", mem="16Gi")

    def iscsi(portal, iqn, ro=False):
        return {"iscsi": {"targetPortal": portal, "iqn": iqn, "lun": 0,
                          "readOnly": ro}}

    def rbd(mons, image, ro=False):
        return {"rbd": {"monitors": mons, "pool": "p", "image": image,
                        "readOnly": ro}}

    cases = [
        # same IQN via DIFFERENT portals: conflict (multi-path)
        (iscsi("10.0.0.1:3260", "iqn.x"), iscsi("10.0.0.2:3260", "iqn.x"),
         False),
        (iscsi("10.0.0.1:3260", "iqn.x"), iscsi("10.0.0.1:3260", "iqn.y"),
         True),
        # same IQN both read-only: allowed
        (iscsi("a", "iqn.x", ro=True), iscsi("b", "iqn.x", ro=True), True),
        # overlapping (not identical) monitor lists: conflict
        (rbd(["m1", "m2"], "img"), rbd(["m2", "m3"], "img"), False),
        # disjoint monitors: no conflict even for the same image
        (rbd(["m1"], "img"), rbd(["m9"], "img"), True),
        # overlap but different image: no conflict
        (rbd(["m1"], "img"), rbd(["m1"], "other"), True),
    ]
    for i, (existing_vol, pending_vol, fits) in enumerate(cases):
        existing = make_pod(f"e{i}", cpu="10m", mem="1Mi", node_name="n1",
                            volumes=[existing_vol])
        pending = make_pod(f"p{i}", cpu="10m", mem="1Mi",
                           volumes=[pending_vol])
        enc = build([node], [existing], [], [])
        golden = CPUScheduler([node], [existing])
        batch = enc.encode_pods([pending])
        _, per_pred = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
        dev = bool(np.asarray(per_pred)[0, PRED_INDEX["NoDiskConflict"], 0])
        assert dev == fits, (i, existing_vol, pending_vol, dev)
        assert golden.predicates(pending, node)["NoDiskConflict"] == fits, i


def test_rbd_many_monitors_do_not_truncate():
    """A 5-monitor RBD volume (standard Ceph HA) must check every monitor
    token — DV grows with the pod's token count, no silent truncation."""
    node = make_node("n1", cpu="8", mem="16Gi")
    mons = [f"m{i}" for i in range(5)]
    existing = make_pod("e", cpu="10m", mem="1Mi", node_name="n1",
                        volumes=[{"rbd": {"monitors": ["m4"], "pool": "p",
                                          "image": "img"}}])
    pending = make_pod("p", cpu="10m", mem="1Mi",
                       volumes=[{"rbd": {"monitors": mons, "pool": "p",
                                         "image": "img"}}])
    enc = build([node], [existing], [], [])
    golden = CPUScheduler([node], [existing])
    batch = enc.encode_pods([pending])
    _, per_pred = filter_batch(enc.snapshot(), batch, FilterConfig(), 0)
    dev = bool(np.asarray(per_pred)[0, PRED_INDEX["NoDiskConflict"], 0])
    assert not dev, "overlap through the 5th monitor must conflict"
    assert golden.predicates(pending, node)["NoDiskConflict"] == dev
