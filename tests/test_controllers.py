"""Controller layer: ReplicaSet reconcile + node lifecycle over the
blackboard (ref pkg/controller/replicaset, pkg/controller/nodelifecycle,
shape at SURVEY.md section 3.5)."""

import threading
import time

import pytest

from kubernetes_tpu.runtime.cluster import LocalCluster, make_cluster_binder, wire_scheduler
from kubernetes_tpu.runtime.controllers import (
    ControllerManager,
    NodeLifecycleController,
    ReplicaSet,
    ReplicaSetController,
    WorkQueue,
    add_replicaset,
    renew_node_lease,
    TAINT_UNREACHABLE,
)
from kubernetes_tpu.runtime.kubemark import HollowFleet
from kubernetes_tpu.runtime.queue import PriorityQueue
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

from fixtures import make_node, make_pod


def _template(labels, cpu="100m"):
    return {
        "metadata": {"labels": dict(labels)},
        "spec": {
            "containers": [
                {"name": "c0", "image": "app:v1",
                 "resources": {"requests": {"cpu": cpu, "memory": "64Mi"}}}
            ]
        },
    }


# ------------------------------------------------------------------ workqueue


def test_workqueue_dedup_and_dirty_requeue():
    q = WorkQueue()
    q.add("a"); q.add("a"); q.add("b")
    assert len(q) == 2
    k = q.get(0.1)
    assert k == "a"
    q.add("a")            # re-added while processing -> dirty
    assert len(q) == 1    # not queued twice
    q.done("a")
    assert len(q) == 2    # requeued after done
    q.get(0.1); q.get(0.1)
    assert q.get(0.01) is None


def test_workqueue_rate_limited_backoff():
    q = WorkQueue(base_delay=0.1)
    q.add_rate_limited("k")
    assert q.get(0.005) is None         # well inside the delay window
    assert q.get(1.0) == "k"            # arrives after the delay


# ----------------------------------------------------------------- replicaset


def _drain(ctrl, n=20):
    while ctrl.process_one(timeout=0.05):
        n -= 1
        if n <= 0:
            break


def test_replicaset_scales_up_and_down():
    cluster = LocalCluster()
    ctrl = ReplicaSetController(cluster)
    rs = ReplicaSet("default", "web", 3, {"app": "web"},
                    _template({"app": "web"}))
    add_replicaset(cluster, rs)
    _drain(ctrl)
    pods = cluster.list("pods")
    assert len(pods) == 3
    assert all(p.metadata.owner_uid == rs.uid for p in pods)
    assert all(p.labels == {"app": "web"} for p in pods)

    # scale down to 1
    rs.replicas = 1
    cluster.update("replicasets", rs)
    _drain(ctrl)
    assert len(cluster.list("pods")) == 1

    # a deleted pod is replaced
    survivor = cluster.list("pods")[0]
    cluster.delete("pods", survivor.namespace, survivor.name)
    _drain(ctrl)
    assert len(cluster.list("pods")) == 1
    assert cluster.list("pods")[0].name != survivor.name


def test_controller_created_pods_drive_the_scheduler():
    """Density via controller-created pods (test/utils/runners.go:1118
    NewSimpleWithControllerCreatePodStrategy): RS -> store -> scheduler ->
    bind -> hollow nodes Running."""
    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    fleet = HollowFleet(cluster, [make_node(f"n{i}", cpu="4") for i in range(4)])
    ctrl = ReplicaSetController(cluster)
    add_replicaset(
        cluster,
        ReplicaSet("default", "web", 12, {"app": "web"},
                   _template({"app": "web"})),
    )
    _drain(ctrl)
    for _ in range(6):
        sched.run_once(timeout=0.3)
        if fleet.total_running >= 12:
            break
    assert fleet.total_running == 12
    assert all(p.spec.node_name for p in cluster.list("pods"))


# -------------------------------------------------------------- nodelifecycle


def test_node_failure_evicts_and_reschedules():
    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    fleet = HollowFleet(cluster, [make_node(f"n{i}", cpu="4") for i in range(3)])
    ctrl = ReplicaSetController(cluster)
    lifecycle = NodeLifecycleController(cluster, grace_period=10.0)
    add_replicaset(
        cluster,
        ReplicaSet("default", "web", 6, {"app": "web"},
                   _template({"app": "web"})),
    )
    _drain(ctrl)
    for _ in range(4):
        sched.run_once(timeout=0.3)
    assert all(p.spec.node_name for p in cluster.list("pods"))

    # heartbeats: n0 goes silent, n1/n2 stay fresh
    t0 = 1000.0
    for n in ("n0", "n1", "n2"):
        renew_node_lease(cluster, n, now=t0)
    lifecycle.monitor(now=t0 + 5)           # all healthy
    assert not lifecycle.evictions
    renew_node_lease(cluster, "n1", now=t0 + 20)
    renew_node_lease(cluster, "n2", now=t0 + 20)
    lifecycle.monitor(now=t0 + 21)          # n0's lease 21s old > 10s grace
    node0 = cluster.get("nodes", "", "n0")
    assert any(t.key == TAINT_UNREACHABLE for t in node0.spec.taints)
    assert node0.status.conditions["Ready"] == "Unknown"
    evicted = [e for e in lifecycle.evictions if e[2] == "n0"]
    assert evicted, "pods on n0 must be evicted"

    # the RS replaces them; the scheduler must avoid the tainted node
    _drain(ctrl)
    for _ in range(4):
        sched.run_once(timeout=0.3)
    pods = cluster.list("pods")
    assert len(pods) == 6
    assert all(p.spec.node_name in ("n1", "n2") for p in pods)

    # recovery: lease renewed -> taint removed, Ready True
    renew_node_lease(cluster, "n0", now=t0 + 30)
    lifecycle.monitor(now=t0 + 31)
    node0 = cluster.get("nodes", "", "n0")
    assert not any(t.key == TAINT_UNREACHABLE for t in node0.spec.taints)
    assert node0.status.conditions["Ready"] == "True"


def test_controller_manager_runs_threaded():
    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    HollowFleet(cluster, [make_node(f"n{i}", cpu="4") for i in range(2)])
    cm = ControllerManager(cluster, grace_period=30.0)
    cm.start(rs_workers=2, monitor_period=0.05)
    try:
        add_replicaset(
            cluster,
            ReplicaSet("default", "api", 4, {"app": "api"},
                       _template({"app": "api"})),
        )
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            sched.run_once(timeout=0.2)
            if len([p for p in cluster.list("pods") if p.spec.node_name]) >= 4:
                break
        bound = [p for p in cluster.list("pods") if p.spec.node_name]
        assert len(bound) == 4
    finally:
        cm.stop()


# --------------------------------------------------------------- deployments


def test_deployment_rolling_update():
    """Template change rolls pods from the v1 ReplicaSet to the v2 one,
    respecting maxSurge/maxUnavailable against READY pods."""
    from kubernetes_tpu.runtime.controllers import (
        Deployment,
        DeploymentController,
        add_deployment,
    )

    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    fleet = HollowFleet(cluster, [make_node(f"n{i}", cpu="8") for i in range(4)])
    rs_ctrl = ReplicaSetController(cluster)
    dep_ctrl = DeploymentController(cluster)

    def tick(n=6):
        for _ in range(n):
            while dep_ctrl.process_one(timeout=0.02):
                pass
            while rs_ctrl.process_one(timeout=0.02):
                pass
            sched.run_once(timeout=0.2)

    dep = Deployment(
        "default", "web", 6, {"app": "web"},
        _template({"app": "web"}), max_surge=2, max_unavailable=1,
    )
    add_deployment(cluster, dep)
    tick()
    rss = cluster.list("replicasets")
    assert len(rss) == 1 and rss[0].replicas == 6
    v1_rs = rss[0]
    assert all(
        p.labels.get("pod-template-hash") for p in cluster.list("pods")
    )
    assert fleet.total_running == 6

    # roll to v2 (different image)
    dep.template = _template({"app": "web"})
    dep.template["spec"]["containers"][0]["image"] = "app:v2"
    cluster.update("deployments", dep)
    tick(12)
    rss = {rs.name: rs for rs in cluster.list("replicasets")}
    assert len(rss) == 2
    v2_rs = next(rs for rs in rss.values() if rs.name != v1_rs.name)
    assert rss[v1_rs.name].replicas == 0
    assert v2_rs.replicas == 6
    pods = cluster.list("pods")
    assert len(pods) == 6
    assert all(p.metadata.owner_uid == v2_rs.uid for p in pods)
    # surge respected: never more than replicas + maxSurge pods existed
    # (spot-check final state; transient surge counts are bounded by RS sums)
    assert len(pods) <= 6 + 2


def test_deployment_recreate_strategy():
    from kubernetes_tpu.runtime.controllers import (
        Deployment,
        DeploymentController,
        add_deployment,
    )

    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    HollowFleet(cluster, [make_node(f"n{i}", cpu="8") for i in range(2)])
    rs_ctrl = ReplicaSetController(cluster)
    dep_ctrl = DeploymentController(cluster)

    def tick(n=6):
        for _ in range(n):
            while dep_ctrl.process_one(timeout=0.02):
                pass
            while rs_ctrl.process_one(timeout=0.02):
                pass
            sched.run_once(timeout=0.2)

    dep = Deployment(
        "default", "api", 3, {"app": "api"},
        _template({"app": "api"}), strategy="Recreate",
    )
    add_deployment(cluster, dep)
    tick()
    assert len([p for p in cluster.list("pods") if p.spec.node_name]) == 3
    dep.template = _template({"app": "api"})
    dep.template["spec"]["containers"][0]["image"] = "app:v2"
    cluster.update("deployments", dep)
    tick(12)
    rss = {rs.name for rs in cluster.list("replicasets")}
    assert len(rss) == 2
    pods = cluster.list("pods")
    assert len(pods) == 3
    hashes = {p.labels["pod-template-hash"] for p in pods}
    assert len(hashes) == 1  # all pods carry the NEW template hash


def test_deployment_rollout_progresses_past_stuck_old_pod():
    """cleanupUnhealthyReplicas analog: an old replica that never became
    ready must not deadlock the rollout."""
    from kubernetes_tpu.runtime.controllers import (
        Deployment,
        DeploymentController,
        add_deployment,
    )

    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    # capacity for only 3 pods of 1 cpu: the 4th old replica stays Pending
    HollowFleet(cluster, [make_node(f"n{i}", cpu="1500m") for i in range(3)])
    rs_ctrl = ReplicaSetController(cluster)
    dep_ctrl = DeploymentController(cluster)

    def tick(n=8):
        for _ in range(n):
            while dep_ctrl.process_one(timeout=0.02):
                pass
            while rs_ctrl.process_one(timeout=0.02):
                pass
            sched.run_once(timeout=0.2)

    dep = Deployment(
        "default", "web", 4, {"app": "web"},
        _template({"app": "web"}, cpu="1"), max_surge=1, max_unavailable=1,
    )
    add_deployment(cluster, dep)
    tick()
    running = [p for p in cluster.list("pods") if p.status.phase == "Running"]
    assert len(running) == 3  # 4th can't fit: permanently unhealthy
    dep.template = _template({"app": "web"}, cpu="1")
    dep.template["spec"]["containers"][0]["image"] = "app:v2"
    cluster.update("deployments", dep)
    tick(16)
    pods = cluster.list("pods")
    hashes = {p.labels["pod-template-hash"] for p in pods
              if p.status.phase == "Running"}
    assert len(hashes) == 1, "rollout must reach the new template"


def test_deployment_delete_cascades():
    from kubernetes_tpu.runtime.controllers import (
        Deployment,
        DeploymentController,
        add_deployment,
    )

    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    HollowFleet(cluster, [make_node("n0", cpu="8")])
    rs_ctrl = ReplicaSetController(cluster)
    dep_ctrl = DeploymentController(cluster)

    def tick(n=6):
        for _ in range(n):
            while dep_ctrl.process_one(timeout=0.02):
                pass
            while rs_ctrl.process_one(timeout=0.02):
                pass
            sched.run_once(timeout=0.2)

    add_deployment(cluster, Deployment(
        "default", "tmp", 3, {"app": "tmp"}, _template({"app": "tmp"}),
    ))
    tick()
    assert len(cluster.list("pods")) == 3
    cluster.delete("deployments", "default", "tmp")
    tick()
    assert cluster.list("replicasets") == []
    assert cluster.list("pods") == []


# ---------------------------------------------------------------------- jobs


def test_job_runs_to_completion():
    """Job with completions=5, parallelism=2: hollow nodes complete pods,
    the controller replaces them until 5 Succeeded, then stops."""
    from kubernetes_tpu.runtime.controllers import Job, JobController, add_job

    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    # complete every job pod on the tick after it starts Running
    HollowFleet(cluster, [make_node(f"n{i}", cpu="4") for i in range(2)],
                completer=lambda p: True)
    ctrl = JobController(cluster)
    add_job(cluster, Job(
        "default", "batchwork", completions=5, parallelism=2,
        template={"metadata": {"labels": {"job": "batchwork"}},
                  "spec": {"containers": [{
                      "name": "c0",
                      "resources": {"requests": {"cpu": "100m"}}}]}},
    ))

    for _ in range(20):
        while ctrl.process_one(timeout=0.02):
            pass
        sched.run_once(timeout=0.2)
        job = cluster.get("jobs", "default", "batchwork")
        if job.complete:
            break
    assert job.complete and job.succeeded == 5
    # never more than `parallelism` active at once is hard to observe after
    # the fact; assert the terminal state instead: exactly 5 succeeded pods
    pods = cluster.list("pods")
    assert sum(1 for p in pods if p.status.phase == "Succeeded") == 5
    assert not [p for p in pods if p.status.phase in ("Pending", "Running")]


def test_job_delete_cascades_pods():
    from kubernetes_tpu.runtime.controllers import Job, JobController, add_job

    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    HollowFleet(cluster, [make_node("n0", cpu="4")])
    ctrl = JobController(cluster)
    add_job(cluster, Job("default", "j", completions=4, parallelism=4,
                         template={"metadata": {}, "spec": {"containers": [
                             {"name": "c0"}]}}))
    _drain(ctrl)
    assert len(cluster.list("pods")) == 4
    cluster.delete("jobs", "default", "j")
    _drain(ctrl)
    assert cluster.list("pods") == []


def test_completed_pods_release_scheduler_resources():
    """The non-terminated informer filter: Succeeded pods decharge the
    cache so their capacity is reusable (job churn does not fill nodes)."""
    from kubernetes_tpu.runtime.controllers import Job, JobController, add_job

    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    # ONE node of 1 cpu; each pod wants 900m -> only one can run at a time
    HollowFleet(cluster, [make_node("n0", cpu="1")], completer=lambda p: True)
    ctrl = JobController(cluster)
    add_job(cluster, Job(
        "default", "churn", completions=4, parallelism=1,
        template={"metadata": {}, "spec": {"containers": [{
            "name": "c0", "resources": {"requests": {"cpu": "900m"}}}]}},
    ))
    for _ in range(24):
        while ctrl.process_one(timeout=0.02):
            pass
        sched.run_once(timeout=0.2)
        job = cluster.get("jobs", "default", "churn")
        if job.complete:
            break
    assert job.complete and job.succeeded == 4
    import numpy as np

    assert float(np.asarray(sched.cache.encoder.a_requested)[:, 0].sum()) == 0.0


def test_job_with_deferred_completion_via_tick():
    """A completer that declines at claim time completes via fleet.tick()
    (the PLEG relist analog) — jobs still converge."""
    from kubernetes_tpu.runtime.controllers import Job, JobController, add_job

    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    gate = {"open": False}
    fleet = HollowFleet(
        cluster, [make_node("n0", cpu="4")],
        completer=lambda p: gate["open"],
    )
    ctrl = JobController(cluster)
    add_job(cluster, Job("default", "slow", completions=2, parallelism=2,
                         template={"metadata": {}, "spec": {"containers": [
                             {"name": "c0",
                              "resources": {"requests": {"cpu": "100m"}}}]}}))
    _drain(ctrl)
    sched.run_once(timeout=0.3)
    assert fleet.total_running == 2     # running, not yet complete
    gate["open"] = True
    assert fleet.tick() == 2            # PLEG sweep completes them
    _drain(ctrl)
    job = cluster.get("jobs", "default", "slow")
    assert job.complete and job.succeeded == 2
