"""Leader election: CAS lease, active/standby failover mid-workload.

Mirrors client-go/tools/leaderelection semantics wired the way
cmd/kube-scheduler/app/server.go:248-262 runs the scheduler: only the
elected instance schedules; when the leader dies, the standby acquires the
expired lease and finishes the workload.
"""

import time

from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.cluster import LocalCluster, make_cluster_binder, wire_scheduler
from kubernetes_tpu.runtime.leaderelection import (
    LeaderElectionConfig,
    LeaderElector,
    run_scheduler_elected,
)
from kubernetes_tpu.runtime.queue import PodBackoff, PriorityQueue
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

from fixtures import make_node, make_pod

FAST = LeaderElectionConfig(
    lease_duration=0.4, renew_deadline=0.3, retry_period=0.05
)


def test_single_elector_acquires_and_renews():
    cluster = LocalCluster()
    el = LeaderElector(cluster, "a", FAST).start()
    try:
        deadline = time.monotonic() + 2.0
        while not el.is_leader and time.monotonic() < deadline:
            time.sleep(0.02)
        assert el.is_leader
        lease = cluster.get("leases", "kube-system", "kube-scheduler")
        assert lease["holder"] == "a"
        assert el.healthy()
    finally:
        el.stop()


def test_standby_does_not_acquire_while_leader_alive():
    cluster = LocalCluster()
    a = LeaderElector(cluster, "a", FAST).start()
    b = LeaderElector(cluster, "b", FAST).start()
    try:
        time.sleep(0.6)  # beyond one lease duration
        assert a.is_leader != b.is_leader  # exactly one leader
    finally:
        a.stop()
        b.stop()


def test_release_on_stop_hands_over_immediately():
    cluster = LocalCluster()
    a = LeaderElector(cluster, "a", FAST).start()
    while not a.is_leader:
        time.sleep(0.02)
    b = LeaderElector(cluster, "b", FAST).start()
    a.stop(release=True)
    try:
        deadline = time.monotonic() + 2.0
        while not b.is_leader and time.monotonic() < deadline:
            time.sleep(0.02)
        assert b.is_leader
    finally:
        b.stop()


def _make_member(cluster, name, bind_counts, bind_delay=0.02):
    cache = SchedulerCache()
    queue = PriorityQueue(backoff=PodBackoff(initial=0.01, max_duration=0.05))
    inner = make_cluster_binder(cluster)

    def binder(pod, node):
        time.sleep(bind_delay)  # slow apiserver: keeps the kill mid-density
        ok = inner(pod, node)
        if ok:
            bind_counts[name] = bind_counts.get(name, 0) + 1
        return ok

    sched = Scheduler(
        cache=cache,
        queue=queue,
        binder=binder,
        config=SchedulerConfig(batch_size=4, disable_preemption=True),
    )
    wire_scheduler(cluster, sched)
    return sched


def test_failover_mid_density_standby_finishes():
    cluster = LocalCluster()
    for i in range(3):
        cluster.add_node(make_node(f"n{i}", cpu="16", mem="32Gi", pods=110))
    counts = {}
    sched_a = _make_member(cluster, "a", counts)
    sched_b = _make_member(cluster, "b", counts)
    el_a = run_scheduler_elected(cluster, sched_a, "a", FAST)
    while not el_a.is_leader:
        time.sleep(0.02)
    el_b = run_scheduler_elected(cluster, sched_b, "b", FAST)

    n_pods = 24
    for i in range(n_pods):
        cluster.add_pod(make_pod(f"d{i}", cpu="100m", mem="64Mi"))

    def bound_count():
        return sum(1 for p in cluster.list("pods") if p.spec.node_name)

    # let the leader schedule part of the workload, then kill it abruptly
    deadline = time.monotonic() + 10.0
    while bound_count() < 6 and time.monotonic() < deadline:
        time.sleep(0.02)
    killed_at = bound_count()
    assert killed_at >= 6
    el_a.stop(release=False)  # crash: no lease handover, standby must expire it

    deadline = time.monotonic() + 15.0
    while bound_count() < n_pods and time.monotonic() < deadline:
        time.sleep(0.05)
    assert bound_count() == n_pods
    assert counts.get("b", 0) > 0  # the standby took over and finished
    assert el_b.is_leader
    el_b.stop()
