"""Device-resident megacycle (ISSUE 12).

Pins the tentpole contracts: a megacycle of K batches places
bit-identically to K chained single-cycle launches (raw engines) AND to
K separate live cycles with host commits in between (both engines,
single-chip and on the 8-virtual-device mesh), ineligible pods fall
back to single cycles with identical placements, the resilience stack
treats a megacycle as one retryable unit (transient relaunch, CPU-
adapter sequential replay) with the invariant checker staying clean
across a fault-interrupted megacycle, chained-state donation is sound
across back-to-back megacycles, prewarm covers the K x width ladder,
the host_stall/fetch_block phase alias reconciles with /debug/perf on
the megacycle path, and the ledger records a megacycle as K replayable
blocks.
"""

import dataclasses
import time

import numpy as np
import pytest

import jax

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.faults import (
    FAULT_PERSISTENT,
    FAULT_TRANSIENT,
    FaultInjector,
    install_injector,
)
from kubernetes_tpu.models.batched import (
    encode_batch_ports,
    make_sequential_scheduler,
)
from kubernetes_tpu.models.megacycle import (
    make_megacycle_scheduler,
    stack_windows,
)
from kubernetes_tpu.ops.priorities import pod_group_onehot
from kubernetes_tpu.runtime import perfobs
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.queue import PodBackoff, PriorityQueue
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

from fixtures import make_node, make_pod

pytestmark = pytest.mark.megacycle

_ZONE = "failure-domain.beta.kubernetes.io/zone"


def _encoder(n_nodes=12, n_groups=4):
    enc = SnapshotEncoder()
    for i in range(n_nodes):
        enc.add_node(make_node(
            f"n{i}", cpu="16", mem="32Gi",
            labels={_ZONE: f"z{i % 3}"},
        ))
    for d in range(n_groups):
        enc.add_spread_selector("default", {"app": f"dep-{d}"})
    return enc


def _windows(K=4, W=8, prefix="p", n_groups=4):
    return [
        [
            make_pod(
                f"{prefix}{k}-{i}", cpu="300m", mem="128Mi",
                labels={"app": f"dep-{(k + i) % n_groups}"},
            )
            for i in range(W)
        ]
        for k in range(K)
    ]


def _encode_all(enc, windows):
    # two passes: a later window can grow a sticky pad dim; the second
    # pass encodes every window at the (now stable) max shapes — the
    # scheduler's _dispatch_megacycle does the same
    batches = [enc.encode_pods(w) for w in windows]
    batches = [enc.encode_pods(w) for w in windows]
    ports = [encode_batch_ports(enc, w) for w in windows]
    return batches, ports


def _host_gc_commit(gc, hosts, batch):
    """Host reference of the megacycle's group-count chaining."""
    gc = np.asarray(gc).copy()
    oh = np.asarray(pod_group_onehot(batch, gc.shape[1]))
    for b, h in enumerate(np.asarray(hosts)):
        if h >= 0:
            gc[h] += oh[b]
    return gc


def _chained_reference(fn, cluster, batches, ports, li0):
    """K single-cycle launches chained by hand: resources through the
    engine's returned cluster, spread counts through the host recount —
    exactly what K live cycles with host commits produce."""
    cl = cluster
    out = []
    for k, (b, p) in enumerate(zip(batches, ports)):
        hosts, cl2 = fn(cl, b, p, np.int32(li0[k]))
        hosts = np.asarray(hosts)
        out.append(hosts)
        cl = dataclasses.replace(
            cl2, group_counts=_host_gc_commit(cl.group_counts, hosts, b)
        )
    return np.stack(out), cl


@pytest.mark.parametrize("engine", ["sequential", "speculative"])
def test_raw_megacycle_identical_to_chained_single_cycles(engine):
    enc = _encoder()
    windows = _windows(K=4, W=8)
    batches, ports = _encode_all(enc, windows)
    cluster = enc.snapshot()
    li0 = np.cumsum([0] + [len(w) for w in windows[:-1]]).astype(np.int32)
    mega = make_megacycle_scheduler(
        engine=engine, zone_key_id=enc.getzone_key
    )
    hosts_k, final = mega(
        cluster, stack_windows(batches), stack_windows(ports), li0
    )
    hosts_k = np.asarray(hosts_k)
    if engine == "sequential":
        fn = make_sequential_scheduler(zone_key_id=enc.getzone_key)
    else:
        # the reference must run the same device program family the
        # megacycle scans (the packed while_loop + in-program redo)
        import kubernetes_tpu.models.speculative as spec_mod

        prev = spec_mod.FORCE_PACKED_PATH
        spec_mod.FORCE_PACKED_PATH = True
        try:
            fn = spec_mod.make_speculative_scheduler(
                zone_key_id=enc.getzone_key
            )
            ref, ref_cl = _chained_reference(fn, cluster, batches, ports, li0)
        finally:
            spec_mod.FORCE_PACKED_PATH = prev
        assert np.array_equal(hosts_k, ref)
        assert np.array_equal(
            np.asarray(final.requested), np.asarray(ref_cl.requested)
        )
        assert np.array_equal(
            np.asarray(final.group_counts), np.asarray(ref_cl.group_counts)
        )
        return
    ref, ref_cl = _chained_reference(fn, cluster, batches, ports, li0)
    assert np.array_equal(hosts_k, ref)
    assert np.array_equal(
        np.asarray(final.requested), np.asarray(ref_cl.requested)
    )
    assert np.array_equal(
        np.asarray(final.nonzero_req), np.asarray(ref_cl.nonzero_req)
    )
    assert np.array_equal(
        np.asarray(final.group_counts), np.asarray(ref_cl.group_counts)
    )
    assert (hosts_k >= 0).sum() > 0


# ------------------------------------------------------------ live path


def _live(K, engine="speculative", nodes=8, pipeline=True, shard=0,
          **cfg_kw):
    cache = SchedulerCache()
    queue = PriorityQueue(
        backoff=PodBackoff(initial=0.01, max_duration=0.05)
    )
    cfg = SchedulerConfig(
        batch_size=32, batch_window_s=0.0, engine=engine,
        disable_preemption=True, batched_commit=True,
        pipeline_commit=pipeline, megacycle_batches=K,
        shard_devices=shard,
        device_backoff_base_s=0.001, device_backoff_max_s=0.005,
        breaker_open_s=0.02,
        **cfg_kw,
    )
    sched = Scheduler(
        cache=cache, queue=queue, binder=lambda p, n: True, config=cfg
    )
    for i in range(nodes):
        cache.add_node(make_node(
            f"n{i}", cpu="64", mem="128Gi", labels={_ZONE: f"z{i % 4}"},
        ))
    for d in range(4):
        cache.encoder.add_spread_selector("default", {"app": f"dep-{d}"})
    return sched, queue


def _drain(sched, queue, budget_s=120.0):
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        got = sched.run_once(timeout=0.0)
        if got == 0 and not sched.pipeline_pending:
            if not queue.has_schedulable():
                break
            time.sleep(0.002)
    sched.flush_pipeline()


def _feed(queue, n, prefix="p"):
    for i in range(n):
        queue.add(make_pod(
            f"{prefix}{i}", cpu="100m", mem="64Mi",
            labels={"app": f"dep-{i % 4}"},
        ))


def _placements(sched):
    return {
        (r.pod.namespace, r.pod.name): r.node
        for r in sched.results if r.node is not None
    }


@pytest.mark.parametrize("engine", ["sequential", "speculative"])
def test_live_megacycle_identical_to_single_cycles(engine):
    """The acceptance pin: the SAME pod stream through megacycleBatches=4
    and =1 binds every pod to the same node — the on-device chain
    (resources + spread counts) reproduces the host commits exactly."""
    s1, q1 = _live(1, engine)
    _feed(q1, 200)
    _drain(s1, q1)
    s4, q4 = _live(4, engine)
    _feed(q4, 200)
    _drain(s4, q4)
    assert s4.megacycles_total > 0, "no megacycle formed"
    assert _placements(s1) == _placements(s4)
    assert len(_placements(s4)) == 200
    for s in (s1, s4):
        assert s.invariants is not None
        assert s.invariants.violations_total() == 0
        assert s.invariants.assert_drained()


@pytest.mark.sharded
def test_live_megacycle_sharded_identity():
    """Megacycles over the 8-virtual-device mesh place identically to
    the single-chip megacycle run AND to single cycles."""
    s_chip, q_chip = _live(4, "speculative", shard=0)
    _feed(q_chip, 160)
    _drain(s_chip, q_chip)
    s_mesh, q_mesh = _live(4, "speculative", shard=8)
    _feed(q_mesh, 160)
    _drain(s_mesh, q_mesh)
    assert s_mesh.megacycles_total > 0
    assert _placements(s_chip) == _placements(s_mesh)
    s_one, q_one = _live(1, "speculative", shard=8)
    _feed(q_one, 160)
    _drain(s_one, q_one)
    assert _placements(s_one) == _placements(s_mesh)


def test_ineligible_pods_fall_back_to_single_cycles():
    """Pods the chain cannot carry (host ports here) must ride the
    single-cycle path — same placements as megacycleBatches=1, zero
    megacycle launches."""
    def feed_ports(queue, n):
        for i in range(n):
            queue.add(make_pod(
                f"hp{i}", cpu="50m", mem="32Mi",
                ports=[{"hostPort": 8000 + i}],
            ))

    s1, q1 = _live(1, "speculative")
    feed_ports(q1, 60)
    _drain(s1, q1)
    s4, q4 = _live(4, "speculative")
    feed_ports(q4, 60)
    _drain(s4, q4)
    assert s4.megacycles_total == 0
    assert _placements(s1) == _placements(s4)
    assert len(_placements(s4)) == 60


def test_megacycle_safe_gate_matrix():
    sched, _ = _live(4)
    plain = make_pod("ok", cpu="50m", labels={"app": "dep-0"})
    assert sched._megacycle_safe([plain])
    gang = make_pod("g", cpu="50m",
                    labels={Scheduler.POD_GROUP_LABEL: "grp"})
    assert not sched._megacycle_safe([gang])
    porty = make_pod("p", cpu="50m", ports=[{"hostPort": 80}])
    assert not sched._megacycle_safe([porty])
    aff = make_pod(
        "a", cpu="50m",
        affinity={"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {"matchLabels": {"app": "dep-0"}},
                "topologyKey": _ZONE,
            }]}},
    )
    assert not sched._megacycle_safe([aff])
    # two spread groups match this pod: the non-lean shape
    sched.cache.encoder.add_spread_selector("default", {"tier": "x"})
    multi = make_pod("m", cpu="50m",
                     labels={"app": "dep-0", "tier": "x"})
    assert not sched._megacycle_safe([multi])
    # ... and scheduler-level gates
    assert sched._megacycle_ready()
    sched.queue.update_nominated_pod(make_pod("nom", cpu="1m"), "n0")
    assert not sched._megacycle_ready()


def test_express_lane_preempts_between_megacycles():
    """The express preemption point survives megacycle mode: express
    pods arriving under a megacycle bulk backlog are served between
    megacycles (one express cycle per run_once iteration), every pod
    places, and the conservation checker stays clean."""
    s, q = _live(
        4, "speculative",
        express_lane=True, express_batch_size=8,
        express_priority_threshold=1000,
    )
    _feed(q, 160)
    for i in range(12):
        p = make_pod(f"x{i}", cpu="10m", mem="16Mi",
                     labels={"app": "dep-0"})
        p.spec.priority = 2000
        q.add(p)
    _drain(s, q)
    assert s.megacycles_total > 0
    placed = _placements(s)
    assert len(placed) == 172
    assert all(("default", f"x{i}") in placed for i in range(12))
    assert s.invariants.violations_total() == 0
    assert s.invariants.assert_drained()


# ---------------------------------------------------------- resilience


@pytest.fixture
def injector():
    inj = FaultInjector(seed=11)
    remove = install_injector(inj)
    yield inj
    remove()


@pytest.mark.chaos
def test_megacycle_transient_fault_retries_whole_unit(injector):
    """A transient fence fault mid-megacycle relaunches the WHOLE K-deep
    launch: placements match the unfaulted run, every popped pod
    resolves exactly once (the invariant checker stays clean)."""
    s_ref, q_ref = _live(4, "sequential")
    _feed(q_ref, 120)
    _drain(s_ref, q_ref)

    s, q = _live(4, "sequential")
    _feed(q, 120)
    injector.arm("fence", kind=FAULT_TRANSIENT, count=1)
    _drain(s, q)
    assert s.megacycles_total > 0
    assert _placements(s) == _placements(s_ref)
    assert s.invariants.violations_total() == 0
    assert s.invariants.assert_drained()
    from kubernetes_tpu.runtime.health import BREAKER_CLOSED

    assert s.device_health.state == BREAKER_CLOSED


@pytest.mark.chaos
def test_megacycle_persistent_fault_degrades_to_sequential_replay(injector):
    """A persistent fault mid-megacycle serves the K batches
    sequentially from the CPU adapter, bit-identically (sequential
    engine: the adapter carries the scan's tie-rotation), with zero
    pods lost."""
    s_ref, q_ref = _live(4, "sequential")
    _feed(q_ref, 120)
    _drain(s_ref, q_ref)

    s, q = _live(4, "sequential")
    _feed(q, 120)
    injector.arm("fence", kind=FAULT_PERSISTENT)
    _drain(s, q)
    injector.disarm()
    assert _placements(s) == _placements(s_ref)
    assert len(_placements(s)) == 120
    assert s.invariants.violations_total() == 0
    assert s.invariants.assert_drained()


@pytest.mark.chaos
def test_megacycle_relaunch_fault_degrades_instead_of_escaping(injector):
    """A classified fault raised by the RELAUNCH dispatch itself (after
    a transient fence fault approved a retry) must feed the same
    retry/degrade policy as the original fault — the CPU adapter serves
    the K batches and no pod is lost or stranded."""
    s_ref, q_ref = _live(4, "sequential")
    _feed(q_ref, 120)
    _drain(s_ref, q_ref)

    s, q = _live(4, "sequential")
    _feed(q, 120)
    injector.arm("fence", kind=FAULT_TRANSIENT, count=1)
    injector.arm("dispatch", kind=FAULT_PERSISTENT)
    _drain(s, q)
    injector.disarm()
    assert _placements(s) == _placements(s_ref)
    assert len(_placements(s)) == 120
    assert s.invariants.violations_total() == 0
    assert s.invariants.assert_drained()


# ----------------------------------------------- chained-state donation


def test_chained_donation_soundness():
    """Two megacycles back-to-back through the donated chained-state
    path: the second consumes the first's returned cluster, results
    match the undonated path, and on accelerator backends the donated
    input buffers are actually dead after the launch (the classic
    use-after-donate footgun this pins against)."""
    enc = _encoder()
    windows = _windows(K=2, W=8, prefix="d1-")
    windows2 = _windows(K=2, W=8, prefix="d2-")
    b1, p1 = _encode_all(enc, windows + windows2)
    b2, p2 = b1[2:], p1[2:]
    b1, p1 = b1[:2], p1[:2]
    cluster = enc.snapshot()
    li0a = np.asarray([0, 8], np.int32)
    li0b = np.asarray([16, 24], np.int32)

    plain = make_megacycle_scheduler(
        engine="sequential", zone_key_id=enc.getzone_key
    )
    ha, mid_ref = plain(cluster, stack_windows(b1), stack_windows(p1), li0a)
    hb, _ = plain(mid_ref, stack_windows(b2), stack_windows(p2), li0b)

    donated = make_megacycle_scheduler(
        engine="sequential", zone_key_id=enc.getzone_key,
        donate_cluster=True,
    )
    dev0 = jax.device_put(cluster)
    ha2, mid = donated(dev0, stack_windows(b1), stack_windows(p1), li0a)
    if jax.default_backend() != "cpu":
        # the donated input's dynamic buffers must be consumed
        assert dev0.requested.is_deleted()
    ha2 = np.asarray(ha2)
    hb2, final = donated(mid, stack_windows(b2), stack_windows(p2), li0b)
    assert np.array_equal(np.asarray(ha), ha2)
    assert np.array_equal(np.asarray(hb), np.asarray(hb2))
    assert mid is not dev0 and final is not mid


def test_live_back_to_back_megacycles_keep_resident_snapshot_coherent():
    """Two megacycles through the live scheduler: the second's dirty-row
    refresh of the resident device snapshot must reflect the first's
    host commits exactly (placements == one long single-cycle run)."""
    s, q = _live(2, "speculative")
    _feed(q, 128, prefix="a")
    _drain(s, q)
    first = s.megacycles_total
    _feed(q, 128, prefix="b")
    _drain(s, q)
    assert s.megacycles_total > first >= 1
    s1, q1 = _live(1, "speculative")
    _feed(q1, 128, prefix="a")
    _drain(s1, q1)
    _feed(q1, 128, prefix="b")
    _drain(s1, q1)
    assert _placements(s) == _placements(s1)


# ------------------------------------------------------------- prewarm


def test_prewarm_covers_megacycle_ladder():
    s, q = _live(4, "speculative", pipeline=False)
    timings = s.prewarm(widths=[8])
    assert 8 in timings
    assert "mega2x8" in timings and "mega4x8" in timings
    # prewarm must not perturb the runtime: rotation untouched, nothing
    # committed, and the next real stream places like a cold scheduler
    assert s._last_index == 0
    assert not s.results
    _feed(q, 64)
    _drain(s, q)
    s_cold, q_cold = _live(4, "speculative", pipeline=False)
    _feed(q_cold, 64)
    _drain(s_cold, q_cold)
    assert _placements(s) == _placements(s_cold)


# ------------------------------------- phase alias + perfobs + ledger


def test_host_stall_alias_reconciles_with_perfobs_on_megacycle_path():
    """ISSUE 12 satellite: the fence wait is recorded ONCE under the
    perfobs vocabulary; phase_seconds keeps fetch_block as a lockstep
    alias, and /debug/perf's host_stall total reconciles with it on a
    megacycle-serving scheduler."""
    s, q = _live(4, "speculative")
    _feed(q, 200)
    _drain(s, q)
    assert s.megacycles_total > 0
    ph = s.phase_seconds
    assert ph["host_stall"] == pytest.approx(ph["fetch_block"], abs=1e-12)
    tot = s.perfobs.summary()["totals_s"]
    assert abs(tot["host_stall"] - ph["host_stall"]) <= (
        0.02 + 0.05 * max(ph["host_stall"], 1e-9)
    )
    samples = s.perfobs.debug_payload()["samples"]
    megas = [smp for smp in samples if "mega" in smp]
    assert megas, "no megacycle samples reached the observatory"
    ks = {tuple(smp["mega"]) for smp in megas}
    assert any(k[1] > 1 for k in ks)
    for smp in samples:
        split_host = sum(smp["split_s"][p] for p in perfobs.HOST_PHASES)
        assert smp["cycle_wall_s"] + 1e-6 >= split_host


def test_ledger_records_megacycle_as_replayable_blocks(tmp_path):
    """The ledger records a K-deep megacycle as K blocks, each
    replaying bit-identically through the single-batch engine against
    the host snapshot its predecessors' commits produced."""
    from kubernetes_tpu.runtime.ledger import DecisionLedger, replay

    path = str(tmp_path / "mega.ledger")
    ledger = DecisionLedger(path=path)
    cache = SchedulerCache()
    queue = PriorityQueue(
        backoff=PodBackoff(initial=0.01, max_duration=0.05)
    )
    s = Scheduler(
        cache=cache, queue=queue, binder=lambda p, n: True,
        config=SchedulerConfig(
            batch_size=16, batch_window_s=0.0, engine="speculative",
            disable_preemption=True, pipeline_commit=True,
            megacycle_batches=4,
        ),
        ledger=ledger,
    )
    for i in range(8):
        cache.add_node(make_node(f"n{i}", cpu="64", mem="128Gi",
                                 labels={_ZONE: f"z{i % 4}"}))
    for d in range(4):
        cache.encoder.add_spread_selector("default", {"app": f"dep-{d}"})
    _feed(queue, 128)
    _drain(s, queue)
    assert s.megacycles_total > 0
    ledger.flush(30.0)
    out = replay(path)
    assert out["bit_identical"], out
    assert out["cycles"] >= 4
    # the /debug/decisions ring marks megacycle sub-batches
    ring = ledger.decisions()
    megas = [e for e in ring if e.get("mega")]
    assert megas and any(e["mega"][1] > 1 for e in megas)


# ------------------------------------------------------ config plumbing


def test_megacycle_config_plumbing():
    from kubernetes_tpu.config.types import KubeSchedulerConfiguration

    cc = KubeSchedulerConfiguration.from_dict({"megacycleBatches": 8})
    assert cc.megacycle_batches == 8
    cfg = SchedulerConfig.from_component_config(cc)
    assert cfg.megacycle_batches == 8
    assert SchedulerConfig().megacycle_batches == 1


def test_adaptive_megacycle_depth_sizing():
    """AIMD sizes K: depth grows only at saturated width under backlog,
    halves on a deadline overrun, decays when the backlog drains."""
    s, q = _live(
        8, "speculative", pipeline=False,
        adaptive_batch=True, batch_size_min=8, cycle_deadline_s=10.0,
    )
    assert s._cur_mega == 1
    s._cur_batch = s.config.batch_size
    for i in range(600):
        q.add(make_pod(f"d{i}", cpu="1m", labels={"app": "dep-0"}))
    s._adapt_batch(0.001)
    assert s._cur_mega == 2
    s._adapt_batch(0.001)
    assert s._cur_mega == 4
    # deadline overrun: multiplicative decrease on depth too
    s._adapt_batch(99.0)
    assert s._cur_mega == 2
    # backlog gone: decay back toward single cycles
    while q.pop_batch(64, 0.0):
        pass
    s._adapt_batch(0.001)
    assert s._cur_mega == 1
