"""Hot-path performance observatory (ISSUE 11).

Pins the tentpole contracts: the per-cycle host/device split reconciles
with the scheduler's phase_seconds wall time on the live path, the
phase x width EWMA matrix fills, the transfer accounting is byte-EXACT
on the incremental dirty-row path, /debug/perf + /debug/profile +
/debug/ serve on both servers (inflight-exempt on the apiserver), the
profiler capture state machine (throttle / in-progress / graceful
unsupported no-op), the heartbeat satellite fields, and the
bench.py --baseline perf-regression gate (self-compare exits 0, a
synthetic regression exits non-zero).
"""

import dataclasses
import json
import logging
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from kubernetes_tpu.codec.transfer import (
    AsyncFetch,
    DeviceSnapshotCache,
    host_fetch,
    transfer_delta,
    transfer_totals,
)
from kubernetes_tpu.runtime import perfobs
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.health import start_health_server
from kubernetes_tpu.runtime.queue import PodBackoff, PriorityQueue
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

from fixtures import make_node, make_pod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _live_scheduler(nodes=4, **cfg_kw):
    cache = SchedulerCache()
    queue = PriorityQueue(backoff=PodBackoff(initial=0.01, max_duration=0.05))
    cfg = SchedulerConfig(
        disable_preemption=True, batch_size=64, batch_window_s=0.0, **cfg_kw
    )
    sched = Scheduler(
        cache=cache, queue=queue, binder=lambda p, n: True, config=cfg
    )
    for i in range(nodes):
        cache.add_node(make_node(f"n{i}", cpu="16", mem="32Gi"))
    return sched, queue


def _drain(sched, queue, budget_s=60.0):
    deadline = time.monotonic() + budget_s
    while queue.has_schedulable() and time.monotonic() < deadline:
        sched.run_once(timeout=0.0)
    sched.flush_pipeline()


# ------------------------------------------------------- cost-model split


def test_cycle_split_reconciles_with_phase_seconds():
    """Acceptance pin: on the live (synchronous) path the host split
    (enqueue + stall + commit) accounts for ~all of each cycle's wall
    clock, and the observatory's totals reconcile with the scheduler's
    own phase_seconds counters — two independent stamp sets that must
    tell one story."""
    sched, queue = _live_scheduler()
    for i in range(300):
        queue.add(make_pod(f"p{i}", cpu="50m", mem="64Mi"))
    _drain(sched, queue)
    po = sched.perfobs
    summary = po.summary()
    assert summary["cycles"] >= 2
    ph = sched.phase_seconds
    tot = summary["totals_s"]
    # same stamps, independent accumulation points: enqueue == the
    # encode+dispatch phases, stall == fetch_block (tight tolerance)
    enq_ref = ph["encode"] + ph["dispatch"]
    assert abs(tot["host_enqueue"] - enq_ref) <= 0.02 + 0.05 * enq_ref
    assert (
        abs(tot["host_stall"] - ph["fetch_block"])
        <= 0.02 + 0.05 * max(ph["fetch_block"], 1e-9)
    )
    # the commit measure covers the WHOLE tail (ledger/telemetry/perf
    # included) so it bounds the phase counter from above
    assert tot["host_commit"] >= ph["commit"] * 0.9 - 0.02
    # the reconciliation: host split sum ~= cycle wall on the sync path
    host = summary["host_s"]
    wall = summary["wall_s"]
    assert wall > 0
    assert host <= wall + 0.02
    assert summary["unaccounted_s"] <= 0.15 * wall + 0.1, summary
    # per-sample invariant: the payload's arithmetic is self-consistent
    for s in po.debug_payload()["samples"]:
        split_host = sum(
            s["split_s"][p] for p in perfobs.HOST_PHASES
        )
        assert s["cycle_wall_s"] + 1e-6 >= split_host
        assert abs(
            s["cycle_wall_s"] - split_host - s["unaccounted_s"]
        ) < 1e-3


def test_ewma_matrix_covers_every_phase_and_width():
    sched, queue = _live_scheduler()
    for i in range(150):
        queue.add(make_pod(f"p{i}", cpu="50m", mem="64Mi"))
    _drain(sched, queue)
    matrix = sched.perfobs.ewma_matrix()
    assert set(matrix) == set(perfobs.PHASES)
    for phase, row in matrix.items():
        assert row, f"phase {phase} has no width entries"
        for width, v in row.items():
            assert int(width) > 0 and v >= 0.0
    # the batch width the engine actually compiled (pow2 pad of 64)
    assert "64" in matrix["host_enqueue"]


def test_degraded_cycle_attributes_to_host():
    """A breaker-open cycle is served by the CPU engine: the sample is
    tagged degraded and carries no device-side seconds."""
    from kubernetes_tpu.runtime.chaos import Disruptions

    sched, queue = _live_scheduler(
        device_retry_max=0, breaker_failure_threshold=1,
        breaker_open_s=10.0, cpu_fallback=True,
    )
    dis = Disruptions(LocalCluster())
    dis.device_lost()
    try:
        queue.add(make_pod("deg", cpu="50m"))
        sched.run_once(timeout=0.2)
        sched.flush_pipeline()
    finally:
        dis.clear_device_faults()
    samples = sched.perfobs.debug_payload()["samples"]
    deg = [s for s in samples if s["degraded"]]
    assert deg, "no degraded sample recorded"
    assert deg[-1]["split_s"]["device_execute"] == 0.0
    assert deg[-1]["split_s"]["d2h_materialize"] == 0.0
    assert sched.perfobs.summary()["degraded_cycles"] >= 1


# --------------------------------------------------- transfer accounting


def test_dirty_row_scatter_byte_accounting_is_exact():
    """Satellite pin: the counter delta equals the nbytes of the arrays
    that ACTUALLY crossed the wire, on the incremental dirty-row path —
    the pow2-padded row-index vector plus the padded row values."""
    from kubernetes_tpu.codec.schema import _pow2

    @dataclasses.dataclass
    class Snap:
        a: np.ndarray
        b: np.ndarray

    cache = DeviceSnapshotCache()
    a = np.zeros((16, 4), np.float32)
    b = np.arange(16, dtype=np.float32)
    before = transfer_totals()
    cache.update(Snap(a=a, b=b))
    d = transfer_delta(before)
    assert d["h2d/snapshot_upload"]["bytes"] == a.nbytes + b.nbytes
    assert d["h2d/snapshot_upload"]["calls"] == 1

    # touch exactly rows 2 and 3 of one field; the other is
    # identity-reused, so ONLY the scatter moves bytes
    a2 = a.copy()
    a2[2] = 1.0
    a2[3] = 2.0
    rows = np.asarray([2, 3], np.int64)
    before = transfer_totals()
    cache.update(Snap(a=a2, b=b), dirty_rows=rows)
    d = transfer_delta(before)
    k = _pow2(len(rows))  # the shape-bucket pad the wire actually pays
    expected = k * np.dtype(np.int32).itemsize + k * a2[0].nbytes
    assert d == {
        "h2d/dirty_scatter": {"bytes": expected, "calls": 1}
    }, d


def test_fetch_accounting_matches_materialized_nbytes():
    import jax.numpy as jnp

    x = jnp.arange(64, dtype=jnp.float32)
    before = transfer_totals()
    out = host_fetch(x)
    d = transfer_delta(before)
    assert d["d2h/fetch"] == {"bytes": out.nbytes, "calls": 1}

    before = transfer_totals()
    f = AsyncFetch(jnp.arange(32, dtype=jnp.int32))
    out = f.result()
    # the worker sets the split AFTER result() may return: wait for the
    # accounting to land
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not transfer_delta(before):
        time.sleep(0.01)
    d = transfer_delta(before)
    assert d["d2h/fetch"] == {"bytes": out.nbytes, "calls": 1}
    # the host/device attribution split the observatory consumes
    assert f.execute_seconds >= 0.0 and f.materialize_seconds >= 0.0
    assert f.execute_seconds + f.materialize_seconds <= f.seconds + 0.05


def test_live_cycle_span_annotated_with_transfer_bytes():
    from kubernetes_tpu.runtime.flightrecorder import FlightRecorder

    rec = FlightRecorder()
    cache = SchedulerCache()
    queue = PriorityQueue()
    sched = Scheduler(
        cache=cache, queue=queue, binder=lambda p, n: True,
        config=SchedulerConfig(disable_preemption=True),
        flight_recorder=rec,
    )
    cache.add_node(make_node("m1", cpu="8", mem="16Gi"))
    queue.add(make_pod("p", cpu="100m"))
    sched.run_once(timeout=0.2)
    sched.flush_pipeline()
    spans = rec.spans()
    assert spans
    attrs = spans[-1].attrs
    assert attrs.get("transfer_bytes", 0) > 0, attrs
    assert "/" in attrs.get("transfer_top_seam", ""), attrs


def test_pipelined_cycle_transfer_deltas_do_not_double_count():
    """Under pipeline_commit a cycle's tail runs AFTER the next cycle's
    dispatch.  The per-cycle delta is taken at the commit FENCE (before
    that next dispatch), so summing every cycle's delta must equal the
    global counters' movement — a tail-time delta would count each
    dispatch's uploads twice."""
    before = transfer_totals()
    sched, queue = _live_scheduler(pipeline_commit=True)
    for i in range(300):
        queue.add(make_pod(f"p{i}", cpu="50m", mem="64Mi"))
    _drain(sched, queue)
    global_delta = transfer_delta(before)
    summed: dict = {}
    for s in sched.perfobs.debug_payload()["samples"]:
        for k, v in s["transfers"].items():
            cell = summed.setdefault(k, {"bytes": 0, "calls": 0})
            cell["bytes"] += v["bytes"]
            cell["calls"] += v["calls"]
    assert summed, "no per-cycle transfer deltas recorded"
    assert summed == global_delta


# ----------------------------------------------------- debug endpoints


def test_debug_perf_and_index_on_health_server():
    sched, queue = _live_scheduler()
    for i in range(100):
        queue.add(make_pod(f"p{i}", cpu="50m"))
    _drain(sched, queue)
    srv = start_health_server()
    try:
        h, p = srv.address
        with urllib.request.urlopen(
            f"http://{h}:{p}/debug/perf", timeout=10
        ) as r:
            assert "application/json" in r.headers.get("Content-Type", "")
            body = json.loads(r.read())
        assert {"summary", "ewma_s", "profiler", "samples"} <= set(body)
        assert body["summary"]["cycles"] >= 1
        assert body["summary"]["transfers"]
        with urllib.request.urlopen(
            f"http://{h}:{p}/debug/perf?limit=1", timeout=10
        ) as r:
            limited = json.loads(r.read())
        assert len(limited["samples"]) == 1
        with urllib.request.urlopen(
            f"http://{h}:{p}/debug/", timeout=10
        ) as r:
            idx = json.loads(r.read())
        eps = idx["endpoints"]
        assert {
            "/debug/traces", "/debug/decisions", "/debug/cluster",
            "/debug/perf", "/debug/profile",
        } <= set(eps)
        for desc in eps.values():
            assert isinstance(desc, str) and desc
    finally:
        srv.stop()


def test_debug_perf_and_index_on_apiserver_inflight_exempt():
    from kubernetes_tpu.apiserver import APIServer
    from kubernetes_tpu.apiserver.fairness import FlowControlConfig

    sched, queue = _live_scheduler(nodes=1)
    queue.add(make_pod("p", cpu="100m"))
    sched.run_once(timeout=0.2)
    sched.flush_pipeline()
    # a starved limiter rejects every non-exempt request: the debug
    # surface must still answer (diagnosing an overload needs it)
    srv = APIServer(
        cluster=LocalCluster(),
        flow_control=FlowControlConfig(
            max_inflight_readonly=1, max_inflight_mutating=1,
            queue_length_per_flow=0, queue_wait_timeout_s=0.01,
        ),
    ).start()
    try:
        with urllib.request.urlopen(
            f"{srv.url}/debug/perf?limit=2", timeout=10
        ) as r:
            body = json.loads(r.read())
        assert "summary" in body and len(body["samples"]) <= 2
        with urllib.request.urlopen(
            f"{srv.url}/debug/", timeout=10
        ) as r:
            idx = json.loads(r.read())
        assert "/debug/perf" in idx["endpoints"]
    finally:
        srv.stop()


def test_debug_perf_body_respects_response_cap():
    from kubernetes_tpu.runtime.ledger import debug_body

    po = perfobs.PerfObservatory(ring_capacity=256)
    for c in range(200):
        po.on_cycle(
            width=64, tier="bulk", degraded=False,
            enqueue_s=0.001, execute_s=0.0005, materialize_s=0.0001,
            stall_s=0.0002, commit_s=0.002, wall_s=0.004,
            transfers={"h2d/snapshot_upload": {"bytes": 100, "calls": 1}},
            trace_id=f"{c:032x}",
        )
    full = json.loads(debug_body(po.debug_payload, ""))
    assert len(full["samples"]) == 200
    capped = json.loads(debug_body(po.debug_payload, "", cap=8192))
    assert 0 < len(capped["samples"]) < 200


# ----------------------------------------------------- profiler capture


class _FakeProfiler:
    def __init__(self, fail_start=False):
        self.fail_start = fail_start
        self.started = []
        self.stopped = 0

    def start_trace(self, d):
        if self.fail_start:
            raise RuntimeError("profiler unsupported on this backend")
        self.started.append(d)

    def stop_trace(self):
        self.stopped += 1


def _patched_capture(monkeypatch, tmp_path, fake, **kw):
    import jax

    monkeypatch.setattr(jax, "profiler", fake)
    return perfobs.ProfilerCapture(profile_dir=str(tmp_path), **kw)


def test_profiler_capture_lifecycle_and_throttle(monkeypatch, tmp_path):
    fake = _FakeProfiler()
    clock = [100.0]
    cap = _patched_capture(
        monkeypatch, tmp_path, fake,
        min_interval_s=30.0, clock=lambda: clock[0],
    )
    out = cap.start(0.05)
    assert out["started"] and out["seconds"] == 0.05
    assert out["dir"].startswith(str(tmp_path))
    # a second start while active reports in-progress, never a
    # concurrent double capture
    again = cap.start(0.05)
    assert not again["started"]
    assert again["reason"] == "capture already in progress"
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and cap.status()["active"]:
        time.sleep(0.01)
    assert fake.stopped == 1 and cap.captures_total == 1
    # throttled until min_interval elapses on the capture's clock
    throttled = cap.start(0.05)
    assert not throttled["started"] and throttled["reason"] == "throttled"
    assert throttled["retry_after_s"] > 0
    clock[0] += 31.0
    assert cap.start(0.05)["started"]
    _wait_inactive(cap)


def _wait_inactive(cap, budget_s=5.0):
    """Let a pending capture timer fire inside THIS test's monkeypatch
    window — a timer outliving the test would stop the next test's
    fake profiler."""
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline and cap.status()["active"]:
        time.sleep(0.01)
    assert not cap.status()["active"]


def test_profiler_capture_unsupported_is_graceful_noop(
    monkeypatch, tmp_path
):
    cap = _patched_capture(
        monkeypatch, tmp_path, _FakeProfiler(fail_start=True)
    )
    out = cap.start(1.0)
    assert out == {
        "started": False, "supported": False,
        "error": "profiler unsupported on this backend",
    }
    # the failed start released the slot: a later start may try again
    assert not cap.status()["active"]


def test_profiler_capture_clamps_seconds(monkeypatch, tmp_path):
    fake = _FakeProfiler()
    cap = _patched_capture(
        monkeypatch, tmp_path, fake, max_seconds=0.2, min_interval_s=0.0
    )
    out = cap.start(9999.0)
    assert out["started"] and out["seconds"] == 0.2
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and cap.status()["active"]:
        time.sleep(0.01)
    assert fake.stopped == 1


def test_profile_request_parses_query(monkeypatch, tmp_path):
    fake = _FakeProfiler()
    cap = _patched_capture(
        monkeypatch, tmp_path, fake, max_seconds=600.0, min_interval_s=0.0
    )
    po = perfobs.PerfObservatory()
    po.profiler = cap
    old = perfobs.get_default()
    perfobs.set_default(po)
    try:
        out = perfobs.profile_request("seconds=0.07")
        assert out["started"] and out["seconds"] == 0.07
        _wait_inactive(cap)
        # malformed seconds falls back to the 2s default
        out = perfobs.profile_request("seconds=bogus")
        assert out["started"] and out["seconds"] == 2.0
        _wait_inactive(cap)
    finally:
        perfobs.set_default(old)


# ----------------------------------------------------------- heartbeat


def test_heartbeat_line_carries_observatory_fields():
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger("kubernetes_tpu")
    handler = _Capture(level=logging.INFO)
    logger.addHandler(handler)
    old_level = logger.level
    logger.setLevel(logging.INFO)
    try:
        sched, queue = _live_scheduler(heartbeat_s=0.01)
        for i in range(40):
            queue.add(make_pod(f"p{i}", cpu="50m"))
        _drain(sched, queue)
        time.sleep(0.02)
        sched.run_once(timeout=0.0)  # idle poll fires the heartbeat
        beats = [r for r in records if r.startswith("heartbeat:")]
        assert beats, "no heartbeat line"
        line = beats[-1]
        for field in ("host_ms=", "dev_ms=", "xfer_top="):
            assert field in line, f"heartbeat missing {field}: {line}"
        # scheduling work happened since the window opened: host time
        # and a top transfer seam must both be visible
        assert "xfer_top=none" not in line or "host_ms=0 " not in line
    finally:
        logger.removeHandler(handler)
        logger.setLevel(old_level)


def test_heartbeat_window_is_a_delta():
    po = perfobs.PerfObservatory()
    po.on_cycle(
        width=8, tier="bulk", degraded=False, enqueue_s=0.5,
        execute_s=0.2, materialize_s=0.0, stall_s=0.1, commit_s=0.4,
        wall_s=1.0,
    )
    host_ms, dev_ms, _ = po.heartbeat_window()
    assert host_ms == pytest.approx(1000.0, abs=1.0)
    assert dev_ms == pytest.approx(200.0, abs=1.0)
    # nothing new since: the window resets
    host_ms, dev_ms, top = po.heartbeat_window()
    assert host_ms == pytest.approx(0.0, abs=1e-6)
    assert dev_ms == pytest.approx(0.0, abs=1e-6)
    assert top == "none"


# -------------------------------------------------- --baseline gate


def _write_artifact(path, **overrides):
    art = {
        "metric": "pods_scheduled_per_sec_5k_nodes",
        "value": 1000.0,
        "unit": "pods/s",
        "p99_schedule_latency_ms": 100.0,
        "cold_start_seconds": 1.0,
        "live_path_pods_per_s": 500.0,
        "detail": {"phases": {"encode": 1.0, "commit": 2.0}},
    }
    art.update(overrides)
    with open(path, "w") as f:
        json.dump(art, f)
    return art


def _run_gate(baseline, current, *extra):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--baseline", str(baseline), "--compare-to", str(current),
         *extra],
        capture_output=True, text=True, timeout=120,
    )


def test_baseline_self_compare_exits_zero(tmp_path):
    """Acceptance pin: an artifact compared against itself is clean."""
    art = tmp_path / "a.json"
    _write_artifact(art)
    out = _run_gate(art, art, "--perf-delta-out",
                    str(tmp_path / "delta.json"))
    assert out.returncode == 0, out.stderr
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["metric"] == "perf_delta" and line["value"] == 1.0
    delta = json.loads((tmp_path / "delta.json").read_text())
    assert not delta["detail"]["regressions"]
    assert {c["name"] for c in delta["detail"]["checks"]} >= {
        "pods_per_s", "p99_ms", "cold_start_seconds",
        "live_path_pods_per_s",
    }


def test_baseline_synthetic_regression_exits_nonzero(tmp_path):
    """Acceptance pin: an injected regression trips the gate."""
    base = tmp_path / "base.json"
    bad = tmp_path / "bad.json"
    _write_artifact(base)
    _write_artifact(bad, value=400.0,
                    p99_schedule_latency_ms=500.0)
    out = _run_gate(base, bad, "--perf-delta-out",
                    str(tmp_path / "delta.json"))
    assert out.returncode == 1, out.stderr
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["value"] == 0.0
    regs = set(line["detail"]["regressions"])
    assert {"pods_per_s", "p99_ms"} <= regs, regs


def test_baseline_accepts_driver_wrapper_format(tmp_path):
    """BENCH_rNN.json files are the driver's {parsed: artifact}
    wrapper; the gate unwraps them."""
    inner = _write_artifact(tmp_path / "inner.json")
    wrapped = tmp_path / "wrapped.json"
    with open(wrapped, "w") as f:
        json.dump({"n": 1, "rc": 0, "tail": "…", "parsed": inner}, f)
    out = _run_gate(wrapped, tmp_path / "inner.json")
    assert out.returncode == 0, out.stderr


def test_compare_artifacts_units():
    import bench

    base = {"value": 100.0, "p99_schedule_latency_ms": 10.0,
            "detail": {"phases": {"encode": 0.1}}}
    # a faster run never regresses; metrics missing on either side skip
    cur = {"value": 200.0}
    d = bench.compare_artifacts(base, cur, tolerance=0.2)
    assert not d["regressions"]
    assert [c["name"] for c in d["checks"]] == ["pods_per_s"]
    # direction matters: throughput down 50% trips, p99 down never does
    d = bench.compare_artifacts(
        base, {"value": 50.0, "p99_schedule_latency_ms": 1.0},
        tolerance=0.2,
    )
    assert d["regressions"] == ["pods_per_s"]
    # phases: relative growth alone is not enough below the absolute
    # floor (0.1s -> 0.3s is 3x but only +0.2s)
    d = bench.compare_artifacts(
        base,
        {"value": 100.0, "detail": {"phases": {"encode": 0.3}}},
        tolerance=0.2,
    )
    assert not d["regressions"]
    d = bench.compare_artifacts(
        {"value": 100.0, "detail": {"phases": {"encode": 1.0}}},
        {"value": 100.0, "detail": {"phases": {"encode": 2.0}}},
        tolerance=0.2,
    )
    assert d["regressions"] == ["phase:encode"]
