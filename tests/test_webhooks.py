"""Dynamic admission webhooks (apiserver/webhooks.py): AdmissionReview
dispatch, JSONPatch mutation, rules/namespaceSelector matching, and
failurePolicy semantics — driven through a REAL http webhook server and
the full APIServer chain.

Reference: staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/
mutating/dispatcher.go, validating/dispatcher.go, rules/rules.go."""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.apiserver.admission import (
    AdmissionDenied,
    default_admission_chain,
)
from kubernetes_tpu.apiserver.webhooks import (
    WebhookDispatcher,
    apply_json_patch,
)
from kubernetes_tpu.runtime.cluster import LocalCluster


class _Hook(BaseHTTPRequestHandler):
    """A configurable admission webhook: the handler delegates to the
    server's `logic(review) -> response_dict`."""

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        review = json.loads(self.rfile.read(n) or b"{}")
        resp = self.server.logic(review)  # type: ignore[attr-defined]
        body = json.dumps({
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": {"uid": review["request"]["uid"], **resp},
        }).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # quiet
        pass


def _start_hook(logic):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Hook)
    srv.logic = logic
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}/admit"


def test_json_patch_ops():
    doc = {"metadata": {"name": "p", "labels": {"a": "1"}},
           "spec": {"containers": [{"name": "c1"}]}}
    out = apply_json_patch(doc, [
        {"op": "add", "path": "/metadata/labels/injected", "value": "yes"},
        {"op": "replace", "path": "/metadata/labels/a", "value": "2"},
        {"op": "add", "path": "/spec/containers/-",
         "value": {"name": "sidecar"}},
        {"op": "remove", "path": "/metadata/name"},
    ])
    assert out["metadata"]["labels"] == {"a": "2", "injected": "yes"}
    assert [c["name"] for c in out["spec"]["containers"]] == ["c1", "sidecar"]
    assert "name" not in out["metadata"]
    assert doc["metadata"]["labels"] == {"a": "1"}  # input untouched
    with pytest.raises(ValueError):
        apply_json_patch(doc, [{"op": "test", "path": "/metadata/name",
                                "value": "other"}])


def test_mutating_webhook_patches_and_validating_rejects():
    """An out-of-process webhook mutates pods (sidecar label), a second
    validating webhook rejects a forbidden image — through the REAL
    apiserver write path (VERDICT r3 #4 'done' criterion)."""
    recorded = []

    def mutate(review):
        req = review["request"]
        recorded.append((req["operation"], req["resource"]["resource"]))
        patch = [{"op": "add", "path": "/metadata/labels",
                  "value": {"injected": "true"}}]
        return {"allowed": True, "patchType": "JSONPatch",
                "patch": base64.b64encode(json.dumps(patch).encode()
                                          ).decode()}

    def validate(review):
        obj = review["request"]["object"]
        images = [c.get("image", "")
                  for c in (obj.get("spec") or {}).get("containers") or []]
        if any("forbidden" in i for i in images):
            return {"allowed": False,
                    "status": {"message": "forbidden image"}}
        return {"allowed": True}

    m_srv, m_url = _start_hook(mutate)
    v_srv, v_url = _start_hook(validate)
    cluster = LocalCluster()
    srv = APIServer(cluster=cluster)
    srv.admission = default_admission_chain(cluster)
    cluster.create("mutatingwebhookconfigurations", {
        "namespace": "", "name": "inject",
        "webhooks": [{
            "name": "inject.test.io",
            "clientConfig": {"url": m_url},
            "rules": [{"operations": ["CREATE"], "resources": ["pods"]}],
            "failurePolicy": "Fail",
        }],
    })
    cluster.create("validatingwebhookconfigurations", {
        "namespace": "", "name": "imagepolicy",
        "webhooks": [{
            "name": "images.test.io",
            "clientConfig": {"url": v_url},
            "rules": [{"operations": ["*"], "resources": ["pods"]}],
            "failurePolicy": "Fail",
        }],
    })
    srv.start()
    try:
        import urllib.error
        import urllib.request

        def post(payload):
            req = urllib.request.Request(
                f"{srv.url}/api/v1/namespaces/default/pods",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read() or b"{}")

        code, body = post({
            "metadata": {"name": "good", "namespace": "default"},
            "spec": {"containers": [{"name": "c", "image": "nginx"}]},
        })
        assert code == 201, body
        pod = cluster.get("pods", "default", "good")
        assert pod.labels.get("injected") == "true", "mutation must land"
        assert ("CREATE", "pods") in recorded
        code, body = post({
            "metadata": {"name": "bad", "namespace": "default"},
            "spec": {"containers": [
                {"name": "c", "image": "forbidden/backdoor"}]},
        })
        assert code == 403
        assert "forbidden image" in json.dumps(body)
        assert cluster.get("pods", "default", "bad") is None
    finally:
        srv.stop()
        m_srv.shutdown()
        v_srv.shutdown()


def test_failure_policy_ignore_survives_down_webhook():
    cluster = LocalCluster()
    dispatcher = WebhookDispatcher(cluster)
    cluster.create("mutatingwebhookconfigurations", {
        "namespace": "", "name": "down",
        "webhooks": [{
            "name": "down.test.io",
            # nothing listens here
            "clientConfig": {"url": "http://127.0.0.1:1/admit"},
            "rules": [{"operations": ["*"], "resources": ["*"]}],
            "failurePolicy": "Ignore",
            "timeoutSeconds": 1,
        }],
    })
    obj = {"metadata": {"name": "p", "namespace": "default"}}
    assert dispatcher("CREATE", "pods", dict(obj)) == obj  # passes through
    # the same webhook with Fail blocks the write
    cluster.update("mutatingwebhookconfigurations", {
        "namespace": "", "name": "down",
        "webhooks": [{
            "name": "down.test.io",
            "clientConfig": {"url": "http://127.0.0.1:1/admit"},
            "rules": [{"operations": ["*"], "resources": ["*"]}],
            "failurePolicy": "Fail",
            "timeoutSeconds": 1,
        }],
    })
    with pytest.raises(AdmissionDenied):
        dispatcher("CREATE", "pods", dict(obj))


def test_rules_and_namespace_selector_matching():
    calls = []

    def hook(review):
        calls.append(review["request"]["resource"]["resource"])
        return {"allowed": True}

    srv, url = _start_hook(hook)
    cluster = LocalCluster()
    cluster.create("namespaces", {"namespace": "", "name": "prod",
                                  "labels": {"env": "prod"}})
    cluster.create("namespaces", {"namespace": "", "name": "dev",
                                  "labels": {"env": "dev"}})
    cluster.create("validatingwebhookconfigurations", {
        "namespace": "", "name": "prod-only",
        "webhooks": [{
            "name": "prod.test.io",
            "clientConfig": {"url": url},
            "rules": [{"operations": ["CREATE"],
                       "resources": ["pods", "deployments"]}],
            "namespaceSelector": {"matchLabels": {"env": "prod"}},
        }],
    })
    d = WebhookDispatcher(cluster)
    try:
        d("CREATE", "pods", {"metadata": {"namespace": "prod",
                                          "name": "a"}})
        assert calls == ["pods"]
        # wrong namespace label: no call
        d("CREATE", "pods", {"metadata": {"namespace": "dev", "name": "b"}})
        assert calls == ["pods"]
        # wrong resource: no call
        d("CREATE", "secrets", {"metadata": {"namespace": "prod",
                                             "name": "c"}})
        assert calls == ["pods"]
        # wrong operation: no call
        d("DELETE", "pods", {"metadata": {"namespace": "prod", "name": "a"}})
        assert calls == ["pods"]
        # matching second resource: called
        d("CREATE", "deployments", {"metadata": {"namespace": "prod",
                                                 "name": "web"}})
        assert calls == ["pods", "deployments"]
    finally:
        srv.shutdown()


def test_webhook_writing_back_to_apiserver_does_not_deadlock():
    """Review regression: webhook dispatch must run OUTSIDE the write
    lock — a webhook whose handler writes to the SAME apiserver (the
    common audit/sidecar pattern) used to deadlock on the lock its own
    admission held."""
    import urllib.request

    cluster = LocalCluster()
    srv = APIServer(cluster=cluster)
    srv.admission = default_admission_chain(cluster)
    srv.start()

    def writeback(review):
        # the webhook records an audit ConfigMap through the front door
        req = urllib.request.Request(
            f"{srv.url}/api/v1/namespaces/default/configmaps",
            data=json.dumps({
                "metadata": {"name": "webhook-audit",
                             "namespace": "default"},
                "data": {"saw": review["request"]["name"]},
            }).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        urllib.request.urlopen(req, timeout=5)
        return {"allowed": True}

    hook_srv, url = _start_hook(writeback)
    cluster.create("validatingwebhookconfigurations", {
        "namespace": "", "name": "writeback",
        "webhooks": [{
            "name": "writeback.test.io",
            "clientConfig": {"url": url},
            "rules": [{"operations": ["CREATE"], "resources": ["pods"]}],
            "failurePolicy": "Fail",
            "timeoutSeconds": 5,
        }],
    })
    try:
        req = urllib.request.Request(
            f"{srv.url}/api/v1/namespaces/default/pods",
            data=json.dumps({
                "metadata": {"name": "audited", "namespace": "default"},
                "spec": {"containers": [{"name": "c"}]},
            }).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 201
        cm = cluster.get("configmaps", "default", "webhook-audit")
        assert cm is not None and cm["data"]["saw"] == "audited"
    finally:
        srv.stop()
        hook_srv.shutdown()


def _start_tls_hook(logic, cred):
    """HTTPS webhook server presenting `cred` (utils/pki Credential)."""
    import ssl
    import tempfile

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Hook)
    srv.logic = logic
    with tempfile.NamedTemporaryFile(suffix=".pem", delete=False) as cf, \
         tempfile.NamedTemporaryFile(suffix=".pem", delete=False) as kf:
        cf.write(cred.cert_pem)
        kf.write(cred.key_pem)
        cert_path, key_path = cf.name, kf.name
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, srv.server_address[1]


def test_service_reference_https_webhook_with_ca_bundle():
    """VERDICT r4 #5: a clientConfig `service:` reference resolves through
    the service's Endpoints, and the dispatcher trusts the per-hook
    caBundle over HTTPS (client.go:43-146).  A private-CA webhook mutates
    a pod; a hook whose caBundle does NOT match the serving cert fails TLS
    and failurePolicy decides."""
    from kubernetes_tpu.utils import metrics as m
    from kubernetes_tpu.utils.pki import CertificateAuthority

    ca = CertificateAuthority.create("webhook-ca")
    cred = ca.issue("hook-svc.default.svc", sans=["127.0.0.1"])
    other_ca = CertificateAuthority.create("untrusted-ca")

    def mutate(review):
        patch = [{"op": "add", "path": "/metadata/labels",
                  "value": {"via": "tls-hook"}}]
        return {"allowed": True,
                "patch": base64.b64encode(json.dumps(patch).encode()).decode(),
                "patchType": "JSONPatch"}

    srv, port = _start_tls_hook(mutate, cred)
    try:
        cluster = LocalCluster()
        for k in ("services", "endpoints", "mutatingwebhookconfigurations"):
            if not cluster.has_kind(k):
                cluster.register_kind(k)
        cluster.create("services", {
            "kind": "Service", "name": "hook-svc", "namespace": "default",
            "metadata": {"name": "hook-svc", "namespace": "default"},
            "spec": {"clusterIP": "127.0.0.1"},
        })
        cluster.create("endpoints", {
            "kind": "Endpoints", "name": "hook-svc", "namespace": "default",
            "metadata": {"name": "hook-svc", "namespace": "default"},
            "subsets": [{"addresses": [{"ip": "127.0.0.1"}],
                         "ports": [{"port": port}]}],
        })
        cluster.create("mutatingwebhookconfigurations", {
            "kind": "MutatingWebhookConfiguration",
            "namespace": "", "name": "tls-hook",
            "metadata": {"name": "tls-hook"},
            "webhooks": [{
                "name": "mutate.tls.example",
                "clientConfig": {
                    "service": {"namespace": "default", "name": "hook-svc",
                                "path": "/admit"},
                    "caBundle": base64.b64encode(ca.cert_pem).decode(),
                },
                "rules": [{"operations": ["CREATE"], "resources": ["pods"]}],
            }],
        })
        dispatch = WebhookDispatcher(cluster)
        before = m.WEBHOOK_LATENCY.total
        out = dispatch("CREATE", "pods", {
            "metadata": {"name": "p1", "namespace": "default"}})
        assert (out.get("metadata") or {}).get("labels") == {"via": "tls-hook"}
        assert m.WEBHOOK_LATENCY.total > before
        assert dispatch.last_latency["mutate.tls.example"] >= 0.0

        # wrong trust: caBundle from a different CA -> TLS handshake fails
        cfg = cluster.get("mutatingwebhookconfigurations", "", "tls-hook")
        cfg = json.loads(json.dumps(cfg))
        cfg["webhooks"][0]["clientConfig"]["caBundle"] = (
            base64.b64encode(other_ca.cert_pem).decode())
        cfg["webhooks"][0]["failurePolicy"] = "Fail"
        cluster.update("mutatingwebhookconfigurations", cfg)
        with pytest.raises(AdmissionDenied):
            dispatch("CREATE", "pods", {
                "metadata": {"name": "p2", "namespace": "default"}})
        # failurePolicy=Ignore: the TLS failure skips the hook instead
        cfg = json.loads(json.dumps(cfg))
        cfg["webhooks"][0]["failurePolicy"] = "Ignore"
        cluster.update("mutatingwebhookconfigurations", cfg)
        out = dispatch("CREATE", "pods", {
            "metadata": {"name": "p3", "namespace": "default"}})
        assert "labels" not in (out.get("metadata") or {})
    finally:
        srv.shutdown()
