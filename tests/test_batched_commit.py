"""Batched/pipelined commit path: state equivalence vs the per-pod loop,
and incremental (dirty-row) snapshot correctness.

The acceptance bar for the batched commit rebuild (ISSUE 1): batched
commit produces byte-identical cache/encoder state and identical emitted
events vs the per-pod loop on a mixed success/FitError/extender-error
batch, and the dirty-row incremental re-encode matches a full re-encode
after adds/deletes/updates.
"""

import dataclasses

import numpy as np
import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.transfer import DeviceSnapshotCache
from kubernetes_tpu.extender.client import ExtenderError
from kubernetes_tpu.runtime import (
    PriorityQueue,
    Scheduler,
    SchedulerCache,
    SchedulerConfig,
)

from fixtures import TEST_DIMS, ZONE_KEY, make_node, make_pod


def snapshots_equal(a, b, msg=""):
    for f in dataclasses.fields(a):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f.name)), np.asarray(getattr(b, f.name)),
            err_msg=f"{msg}field {f.name}",
        )


def encoder_state_equal(e1, e2, same_slots=True):
    """Byte-identical snapshot tensors + equivalent pod bookkeeping.

    same_slots=False relaxes the pod-ARENA slot ids (m): when a bind fails
    mid-batch, the per-pod loop frees the slot before later pods assume
    (they reuse it) while the batched path assumes the whole batch first —
    a pure permutation of an internal index; each pod's own arena row must
    still carry identical content either way."""
    snapshots_equal(e1.snapshot(full=True), e2.snapshot(full=True))
    assert set(e1.pods) == set(e2.pods)
    for key, r1 in e1.pods.items():
        r2 = e2.pods[key]
        if same_slots:
            assert r1.m == r2.m, key
        assert (r1.node_row, r1.priority) == (r2.node_row, r2.priority)
        np.testing.assert_array_equal(r1.req, r2.req)
        np.testing.assert_array_equal(r1.nonzero, r2.nonzero)
        for enc, rec in ((e1, r1), (e2, r2)):
            assert bool(enc.p_alive[rec.m])
            assert enc.p_node[rec.m] == rec.node_row
            assert enc.p_ns[rec.m] == enc.interner.lookup(rec.key[0])
    assert e1.generation == e2.generation


# ---------------------------------------------------------- encoder batch


def _mixed_pods(n=10):
    pods = []
    for i in range(n):
        kw = dict(
            cpu=f"{100 + 10 * (i % 3)}m", mem="128Mi",
            labels={"app": f"dep-{i % 3}", "idx": str(i)},
            node_name=f"n{i % 4}",
        )
        if i % 4 == 0:
            kw["ports"] = [{"hostPort": 8000 + i, "protocol": "TCP"}]
        if i % 5 == 0:
            kw["volumes"] = [
                {"gcePersistentDisk": {"pdName": f"pd-{i % 2}"}}
            ]
        if i == 3:
            # affinity term with NOVEL strings: interner id assignment
            # must follow add_pod's per-pod order (labels then terms per
            # pod) or every interned-id tensor diverges afterwards
            kw["affinity"] = {"podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [{
                    "labelSelector": {
                        "matchLabels": {"novel-sel-key": "novel-sel-val"}},
                    "topologyKey": "novel.example.com/topo",
                }]}}
        if i == 7:
            kw["node_name"] = "absent-node"  # unassigned row (-1)
        pods.append(make_pod(f"p{i}", **kw))
    return pods


def test_add_pods_matches_sequential_add_pod():
    encs = [SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)]
    for enc in encs:
        for i in range(4):
            enc.add_node(make_node(
                f"n{i}", cpu="8", mem="16Gi",
                labels={ZONE_KEY: f"z-{i % 2}"},
            ))
        enc.add_spread_selector("default", {"app": "dep-0"})
    pods = _mixed_pods()
    for p in pods:
        encs[0].add_pod(p)
    encs[1].add_pods(pods)
    encoder_state_equal(encs[0], encs[1])
    # the interner vocabularies (and therefore every id-bearing tensor,
    # not just the ones compared above) assigned ids in the same order
    assert len(encs[0].interner) == len(encs[1].interner)
    assert encs[0].interner.lookup("novel-sel-val") == \
        encs[1].interner.lookup("novel-sel-val")


def test_add_pods_duplicate_keys_in_one_batch():
    """Degenerate but legal: the same pod key twice in one batch.  The
    per-pod loop replaces the earlier record; the batched path must not
    leak a ghost arena slot double-charging the node."""
    encs = [SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)]
    for enc in encs:
        enc.add_node(make_node("n0", cpu="8", mem="16Gi"))
    dup_a = make_pod("dup", cpu="100m", mem="64Mi", node_name="n0")
    dup_b = make_pod("dup", cpu="300m", mem="256Mi", node_name="n0")
    other = make_pod("other", cpu="50m", mem="32Mi", node_name="n0")
    for p in (dup_a, other, dup_b):
        encs[0].add_pod(p)
    encs[1].add_pods([dup_a, other, dup_b])
    encoder_state_equal(encs[0], encs[1])
    row = encs[1].node_rows["n0"]
    assert encs[1].a_requested[row, 0] == 350.0  # 300 + 50, not 450


def test_add_pods_replaces_existing_records():
    encs = [SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)]
    for enc in encs:
        enc.add_node(make_node("n0", cpu="8", mem="16Gi"))
        enc.add_pod(make_pod("dup", cpu="100m", mem="64Mi", node_name="n0"))
    updated = make_pod("dup", cpu="300m", mem="256Mi", node_name="n0")
    encs[0].add_pod(updated)
    encs[1].add_pods([updated])
    encoder_state_equal(encs[0], encs[1])


# ------------------------------------------------------- commit equivalence


class _FailingExtender:
    """Minimal extender double: non-ignorable filter error for one pod."""

    class _Cfg:
        filter_verb = "filter"
        prioritize_verb = ""
        bind_verb = ""

    config = _Cfg()
    is_ignorable = False
    is_binder = False
    supports_preemption = False

    def __init__(self, fail_name):
        self.fail_name = fail_name

    def is_interested(self, pod):
        return pod.name == self.fail_name

    def filter(self, pod, names):
        raise ExtenderError("extender down")


def _mk_scheduler(batched, pipeline=False, with_extender=True):
    cache = SchedulerCache(SnapshotEncoder(TEST_DIMS))
    for i in range(6):
        cache.add_node(make_node(
            f"n{i}", cpu="4", mem="8Gi", pods=20,
            labels={ZONE_KEY: f"z-{i % 2}"},
        ))
    queue = PriorityQueue()
    binder = lambda pod, node: pod.name != "bind-fail"  # noqa: E731
    sched = Scheduler(
        cache=cache,
        queue=queue,
        binder=binder,
        config=SchedulerConfig(
            batch_size=16, engine="sequential", disable_preemption=True,
            batched_commit=batched, pipeline_commit=pipeline,
        ),
        extenders=[_FailingExtender("ext-fail")] if with_extender else None,
    )
    return sched


def _commit_batch_pods():
    pods = [make_pod(f"ok-{i}", cpu="200m", mem="256Mi",
                     labels={"app": "a"}) for i in range(6)]
    # FitError: nothing can hold 64 cpus
    pods.append(make_pod("fit-fail", cpu="64", mem="128Gi"))
    # non-ignorable extender error
    pods.append(make_pod("ext-fail", cpu="100m", mem="64Mi"))
    # assumed then rejected by the binder (optimistic rollback)
    pods.append(make_pod("bind-fail", cpu="100m", mem="64Mi"))
    pods.append(make_pod("ok-last", cpu="100m", mem="64Mi"))
    return pods


def _event_tuples(recorder):
    return [
        (e.kind, e.namespace, e.name, e.type, e.reason, e.message, e.count)
        for e in recorder.events()
    ]


def _queue_state(q):
    return (
        sorted(q._unschedulable),
        sorted(q._active_entry),
        sorted(q._backoff_entry),
    )


def test_batched_commit_state_equivalent_to_perpod_loop():
    """Mixed success / FitError / extender-error / bind-failure batch: the
    batched commit path must leave byte-identical encoder state, identical
    events (order included), identical results and queue state."""
    s_batched = _mk_scheduler(batched=True)
    s_perpod = _mk_scheduler(batched=False)
    pods = _commit_batch_pods()
    r1 = s_batched.schedule_cycle(list(pods))
    r2 = s_perpod.schedule_cycle(list(pods))

    assert [(r.pod.name, r.node) for r in r1] == [
        (r.pod.name, r.node) for r in r2
    ]
    # the batch really was mixed
    by_name = {r.pod.name: r.node for r in r1}
    assert by_name["fit-fail"] is None
    assert by_name["ext-fail"] is None
    assert by_name["bind-fail"] is None
    assert by_name["ok-0"] is not None and by_name["ok-last"] is not None

    encoder_state_equal(
        s_batched.cache.encoder, s_perpod.cache.encoder, same_slots=False
    )
    assert set(s_batched.cache._assumed) == set(s_perpod.cache._assumed)
    assert _event_tuples(s_batched.recorder) == _event_tuples(s_perpod.recorder)
    assert _queue_state(s_batched.queue) == _queue_state(s_perpod.queue)


def test_pipelined_commit_matches_sync_run():
    """Double-buffered cycles must converge to the same cache state and
    placement set as strictly synchronous cycles."""
    s_pipe = _mk_scheduler(batched=True, pipeline=True, with_extender=False)
    s_sync = _mk_scheduler(batched=True, pipeline=False, with_extender=False)
    waves = [
        [make_pod(f"w{w}-p{i}", cpu="150m", mem="128Mi",
                  labels={"app": f"dep-{w}"})
         for i in range(5)]
        for w in range(4)
    ]
    for s in (s_pipe, s_sync):
        placed = 0
        for wave in waves:
            for p in wave:
                s.queue.add(p)
            placed += s.run_once(timeout=0.05)
        placed += s.flush_pipeline()
        assert placed == 20
    assert s_pipe._in_flight is None
    encoder_state_equal(s_pipe.cache.encoder, s_sync.cache.encoder)
    got_pipe = {(r.pod.name, r.node) for r in s_pipe.results}
    got_sync = {(r.pod.name, r.node) for r in s_sync.results}
    assert got_pipe == got_sync


def test_batched_commit_e2e_survives_bind_echo_delete():
    """A bind's informer echo deletes the bound pod from the queue —
    consuming its enqueue stamp.  The batched tail must take stamps BEFORE
    the bind fan-out, or the e2e histogram silently loses the queue wait.
    The binder here deletes synchronously: the worst-case echo timing."""
    import time

    from kubernetes_tpu.utils import metrics as m

    cache = SchedulerCache(SnapshotEncoder(TEST_DIMS))
    cache.add_node(make_node("n0", cpu="4", mem="8Gi"))
    queue = PriorityQueue()
    sched = Scheduler(
        cache=cache, queue=queue,
        binder=lambda pod, node: queue.delete(pod) or True,
        config=SchedulerConfig(
            batch_size=4, engine="sequential", disable_preemption=True,
        ),
    )
    fresh = m.Histogram("test_e2e_batched", "")
    orig = m.E2E_LATENCY
    m.E2E_LATENCY = fresh
    try:
        queue.add(make_pod("echoed", cpu="100m", mem="64Mi"))
        time.sleep(0.03)
        assert sched.run_once(timeout=0.2) == 1
    finally:
        m.E2E_LATENCY = orig
    assert fresh.total == 1
    assert fresh.sum >= 0.03  # queue wait included despite the echo delete


# ------------------------------------------------------ incremental encode


def test_incremental_snapshot_matches_full_reencode():
    """Dirty-row re-encode == full re-encode across adds/deletes/updates of
    both nodes and pods, with snapshots interleaved so the cow path (not
    the full-rebuild path) is what's being exercised."""
    enc = SnapshotEncoder(TEST_DIMS)
    for i in range(6):
        enc.add_node(make_node(
            f"n{i}", cpu="8", mem="16Gi",
            labels={ZONE_KEY: f"z-{i % 3}"},
        ))
    enc.add_spread_selector("default", {"app": "a"})

    def check(msg):
        inc = enc.snapshot()
        full = enc.snapshot(full=True)
        snapshots_equal(inc, full, msg=msg + ": ")

    check("initial")
    # pod adds (single + batched)
    enc.add_pod(make_pod("p0", cpu="100m", mem="64Mi",
                         labels={"app": "a"}, node_name="n0"))
    check("pod add")
    enc.add_pods([
        make_pod(f"p{i}", cpu="200m", mem="128Mi", labels={"app": "a"},
                 node_name=f"n{i % 3}",
                 ports=[{"hostPort": 9000 + i, "protocol": "TCP"}])
        for i in range(1, 5)
    ])
    check("batched pod add")
    # node label update (topology move)
    enc.update_node(make_node("n1", cpu="8", mem="16Gi",
                              labels={ZONE_KEY: "z-9"}))
    check("node update")
    # pod delete
    enc.remove_pod(make_pod("p2", node_name="n2"))
    check("pod delete")
    # node delete (detaches resident pods) + row-reusing re-add
    enc.remove_node("n0")
    check("node delete")
    enc.add_node(make_node("n6", cpu="2", mem="4Gi",
                           labels={ZONE_KEY: "z-1"}))
    check("row reuse")
    # unchanged state: incremental returns shared (identity) leaves
    s1 = enc.snapshot()
    s2 = enc.snapshot()
    assert s2.label_keys is s1.label_keys
    assert s2.requested is s1.requested


def test_incremental_snapshot_unchanged_fields_share_identity():
    enc = SnapshotEncoder(TEST_DIMS)
    for i in range(4):
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    s1 = enc.snapshot()
    enc.add_pod(make_pod("p", cpu="100m", mem="64Mi", node_name="n1"))
    s2 = enc.snapshot()
    # pod commits touch only the aggregate fields
    assert s2.label_keys is s1.label_keys
    assert s2.taint_key is s1.taint_key
    assert s2.topo_pairs is s1.topo_pairs
    assert s2.requested is not s1.requested
    row = enc.node_rows["n1"]
    assert s2.requested[row, 0] == 100.0
    assert s1.requested[row, 0] == 0.0  # old snapshot untouched (cow)


def test_device_snapshot_cache_dirty_row_scatter():
    """update(cluster, dirty_rows=...) must leave device contents equal to
    a fresh full upload through adds/commits/updates/removes."""
    enc = SnapshotEncoder(TEST_DIMS)
    for i in range(8):
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi",
                               labels={ZONE_KEY: f"z-{i % 2}"}))
    cache = DeviceSnapshotCache()

    def sync_and_check(msg):
        snap = enc.snapshot()
        dirty = enc.take_dirty_rows()
        dev = cache.update(snap, dirty_rows=dirty)
        full = enc.snapshot(full=True)
        for f in dataclasses.fields(full):
            np.testing.assert_array_equal(
                np.asarray(getattr(dev, f.name)),
                np.asarray(getattr(full, f.name)),
                err_msg=f"{msg}: field {f.name}",
            )

    sync_and_check("initial (full upload)")
    # a small commit: exactly the scatter-eligible shape (1 row of 8)
    enc.add_pod(make_pod("p0", cpu="500m", mem="512Mi", node_name="n3"))
    sync_and_check("single-row commit")
    enc.add_pods([
        make_pod(f"q{i}", cpu="100m", mem="64Mi", node_name=f"n{i}")
        for i in range(2)
    ])
    sync_and_check("two-row batched commit")
    enc.update_node(make_node("n5", cpu="2", mem="4Gi",
                              labels={ZONE_KEY: "z-7"}))
    sync_and_check("node update")
    enc.remove_pod(make_pod("q0", node_name="n0"))
    sync_and_check("pod remove")
    enc.remove_node("n7")
    sync_and_check("node remove")


def test_take_dirty_rows_accumulates_across_snapshots():
    """A snapshot taken WITHOUT a device update (the gang launch path) must
    not lose its rows for the next update's scatter."""
    enc = SnapshotEncoder(TEST_DIMS)
    for i in range(8):
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    cache = DeviceSnapshotCache()
    cache.update(enc.snapshot(), dirty_rows=enc.take_dirty_rows())
    enc.add_pod(make_pod("a", cpu="100m", mem="64Mi", node_name="n1"))
    enc.snapshot()          # consumed by someone else; no take, no update
    enc.add_pod(make_pod("b", cpu="100m", mem="64Mi", node_name="n2"))
    snap = enc.snapshot()
    dirty = enc.take_dirty_rows()
    # rows from BOTH snapshots must be in the take
    rows = set(np.asarray(dirty).tolist())
    assert {enc.node_rows["n1"], enc.node_rows["n2"]} <= rows
    dev = cache.update(snap, dirty_rows=dirty)
    full = enc.snapshot(full=True)
    np.testing.assert_array_equal(
        np.asarray(dev.requested), np.asarray(full.requested)
    )


def test_take_dirty_rows_full_rebuild_returns_none():
    enc = SnapshotEncoder(TEST_DIMS)
    enc.add_node(make_node("n0", cpu="4", mem="8Gi"))
    enc.snapshot()
    enc.take_dirty_rows()
    # force an arena regrow (mark-all) by exceeding node capacity
    for i in range(1, 3 * TEST_DIMS.N):
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    enc.snapshot()
    assert enc.take_dirty_rows() is None
