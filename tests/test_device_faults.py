"""Device-fault resilience over the live Scheduler (ISSUE 3 acceptance):

* classified transient faults retry the SAME in-flight batch with backoff;
* a persistent device-lost trips the breaker and the workload completes
  through the CPU degraded path — zero pods lost, no hang;
* breaker transitions closed -> open -> half_open -> closed are emitted as
  Events/metrics and the device path restores automatically when the
  injection stops;
* degraded CPU cycles place bit-identically to the device path on the same
  snapshot;
* the fault matrix (every injection site x kind) never loses a pod.

Everything is seeded and deterministic (codec/faults.FaultInjector), all
sleeps <= 0.1s, runs under JAX_PLATFORMS=cpu inside tier-1.
"""

import time

import numpy as np
import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.faults import (
    FAULT_CORRUPT,
    FAULT_PERSISTENT,
    FAULT_SLOW,
    FAULT_TRANSIENT,
    SITES,
    FaultInjector,
    PersistentDeviceError,
    classify_device_error,
    install_injector,
)
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.health import (
    BREAKER_CLOSED,
    BREAKER_OPEN,
    DeviceHealth,
)
from kubernetes_tpu.runtime.queue import PriorityQueue
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.utils import metrics as m

from fixtures import TEST_DIMS, make_node, make_pod

pytestmark = pytest.mark.chaos


@pytest.fixture
def injector():
    inj = FaultInjector(seed=7)
    remove = install_injector(inj)
    yield inj
    remove()


def _sched(n_nodes=4, cpu="8", **cfg_kw):
    cache = SchedulerCache(SnapshotEncoder(TEST_DIMS))
    for i in range(n_nodes):
        cache.add_node(make_node(f"n{i}", cpu=cpu, mem="8Gi"))
    kw = dict(
        batch_size=8,
        device_backoff_base_s=0.001,
        device_backoff_max_s=0.005,
        breaker_open_s=0.02,
    )
    kw.update(cfg_kw)
    return Scheduler(
        cache=cache, queue=PriorityQueue(), config=SchedulerConfig(**kw)
    )


def _pods(n, prefix="p", cpu="100m"):
    return [make_pod(f"{prefix}{i}", cpu=cpu, mem="128Mi") for i in range(n)]


def _no_pod_lost(sched, pods):
    """The invariant: every pod handed to the scheduler is either bound
    (present in the encoder charged to a node) or still reachable through
    the queue (active/backoff/unschedulable)."""
    enc = sched.cache.encoder
    for p in pods:
        key = (p.namespace, p.name)
        rec = enc.pods.get(key)
        bound = rec is not None and rec.node_row >= 0
        queued = (
            key in sched.queue._active_entry
            or key in sched.queue._backoff_entry
            or key in sched.queue._unschedulable
        )
        assert bound or queued, f"pod {key} lost (neither bound nor queued)"


# --------------------------------------------------------- classification


def test_classification_maps_xla_status_markers():
    assert classify_device_error(
        RuntimeError("UNAVAILABLE: socket closed")
    ) == FAULT_TRANSIENT
    assert classify_device_error(
        RuntimeError("INTERNAL: device lost")
    ) == FAULT_PERSISTENT
    assert classify_device_error(ValueError("shape mismatch")) is None
    assert classify_device_error(
        PersistentDeviceError("gone")
    ) == FAULT_PERSISTENT


# ------------------------------------------------------- transient retries


def test_transient_fence_fault_retries_same_batch(injector):
    injector.arm("fence", kind=FAULT_TRANSIENT, count=1)
    s = _sched()
    before = m.FAULT_RETRIES.value(**{"class": "transient"})
    pods = _pods(4)
    res = s.schedule_cycle(pods)
    assert all(r.node is not None for r in res)
    assert s.device_health.state == BREAKER_CLOSED
    assert s.device_health.consecutive_failures == 0  # healed by success
    assert m.FAULT_RETRIES.value(**{"class": "transient"}) == before + 1
    assert injector.log == [("fence", FAULT_TRANSIENT)]
    # operator audit trail: the fault was eventful even though it healed
    assert s.recorder.events(reason="DeviceFault")


def test_transient_streak_trips_breaker_and_batch_degrades(injector):
    injector.arm("fence", kind=FAULT_TRANSIENT)  # unlimited
    s = _sched(breaker_failure_threshold=3, device_retry_max=5,
               breaker_open_s=60.0)
    deg0 = m.DEGRADED_CYCLES.value
    pods = _pods(4)
    res = s.schedule_cycle(pods)
    # threshold consecutive transients opened the breaker mid-retry; the
    # batch itself was served by the CPU engine — nothing lost
    assert all(r.node is not None for r in res)
    assert s.device_health.state == BREAKER_OPEN
    assert s.device_health.fault_counts[FAULT_TRANSIENT] == 3
    assert m.DEGRADED_CYCLES.value == deg0 + 1
    assert s.recorder.events(reason="BreakerOpen")


# --------------------------------------- persistent fault -> degraded e2e


def test_persistent_fault_completes_workload_on_cpu_then_recovers(injector):
    """The acceptance-criterion e2e: a persistent device fault mid-run ->
    the live scheduler completes the workload via the CPU degraded path
    (no pod lost, no hang), emits breaker Events/metrics, and restores the
    device path automatically once injection stops."""
    s = _sched(n_nodes=4, batch_size=4)
    all_pods = _pods(4, prefix="warm") + _pods(8, prefix="dark") + _pods(
        4, prefix="heal"
    )
    warm, dark, heal = all_pods[:4], all_pods[4:12], all_pods[12:]
    # phase 1: healthy device
    for p in warm:
        s.queue.add(p)
    placed = sum(s.run_once(timeout=0.05) for _ in range(2))
    assert placed == 4
    assert s.device_health.state == BREAKER_CLOSED
    # phase 2: device lost mid-run
    injector.arm("fence", kind=FAULT_PERSISTENT)
    deg0 = m.DEGRADED_CYCLES.value
    for p in dark:
        s.queue.add(p)
    t0 = time.monotonic()
    placed = 0
    for _ in range(6):
        placed += s.run_once(timeout=0.05)
        if placed >= 8:
            break
    assert placed == 8, "workload must complete through the CPU path"
    assert time.monotonic() - t0 < 10.0  # no hang
    _no_pod_lost(s, all_pods[:12])
    assert s.device_health.state == BREAKER_OPEN
    assert m.DEGRADED_CYCLES.value > deg0
    assert m.BREAKER_STATE.value == 2.0
    assert s.recorder.events(reason="BreakerOpen")
    assert s.recorder.events(reason="DeviceFault")
    # phase 3: injection stops; cool-down elapses; canary restores device
    injector.disarm()
    time.sleep(s.config.breaker_open_s + 0.005)
    for p in heal:
        s.queue.add(p)
    placed = sum(s.run_once(timeout=0.05) for _ in range(3))
    assert placed == 4
    assert s.device_health.state == BREAKER_CLOSED
    assert ("open", "half_open") in s.device_health.transitions
    assert ("half_open", "closed") in s.device_health.transitions
    assert s.recorder.events(reason="BreakerClosed")
    _no_pod_lost(s, all_pods)


def test_failed_canary_reopens_breaker(injector):
    injector.arm("fence", kind=FAULT_PERSISTENT)
    s = _sched(breaker_open_s=0.01)
    res = s.schedule_cycle(_pods(4))
    assert all(r.node is not None for r in res)
    assert s.device_health.state == BREAKER_OPEN
    time.sleep(0.015)  # cool-down elapses; next cycle is the canary
    res2 = s.schedule_cycle(_pods(4, prefix="q"))
    assert all(r.node is not None for r in res2)
    assert s.device_health.state == BREAKER_OPEN  # canary failed, re-open
    assert ("open", "half_open") in s.device_health.transitions
    assert ("half_open", "open") in s.device_health.transitions


# ------------------------------------------------- degraded == device path


def test_degraded_cpu_placements_bit_identical_to_device():
    """Same snapshot, same batches: the CPU degraded path must place every
    pod on exactly the node the device path picks (winner rows, rotation
    ties, sequential in-batch commits included)."""

    def build(trip):
        # heterogeneous nodes (distinct scores) + an identical pair (tie
        # rotation must match select_host's row-order + last_index contract)
        cache = SchedulerCache(SnapshotEncoder(TEST_DIMS))
        for name, cpu in (
            ("a", "2"), ("b", "4"), ("c", "8"), ("d", "8"), ("e", "16")
        ):
            cache.add_node(make_node(name, cpu=cpu, mem="16Gi"))
        s = Scheduler(
            cache=cache, queue=PriorityQueue(),
            config=SchedulerConfig(
                batch_size=8, engine="sequential", breaker_open_s=60.0
            ),
        )
        if trip:
            s.device_health.trip()
        return s

    dev, cpu = build(trip=False), build(trip=True)
    for batch_no in range(3):  # several batches: last_index advances
        pods_dev = _pods(6, prefix=f"b{batch_no}-", cpu="300m")
        pods_cpu = _pods(6, prefix=f"b{batch_no}-", cpu="300m")
        rd = dev.schedule_cycle(pods_dev)
        rc = cpu.schedule_cycle(pods_cpu)
        got_dev = [(r.pod.name, r.node) for r in rd]
        got_cpu = [(r.pod.name, r.node) for r in rc]
        assert got_dev == got_cpu, f"batch {batch_no} diverged"
    assert cpu.device_health.state == BREAKER_OPEN  # never probed (60s)
    assert dev.device_health.state == BREAKER_CLOSED


# ------------------------------------------------------ other fault kinds


def test_dispatch_fault_no_fallback_requeues_batch(injector):
    injector.arm("dispatch", kind=FAULT_PERSISTENT)
    s = _sched(cpu_fallback=False)
    pods = _pods(4)
    with pytest.raises(PersistentDeviceError):
        s.schedule_cycle(pods)
    _no_pod_lost(s, pods)
    assert len(s.queue) == 4


def test_corrupted_fetch_detected_and_retried(injector):
    injector.arm("fetch", kind=FAULT_CORRUPT, count=1)
    s = _sched(disable_preemption=True)
    res = s.schedule_cycle(_pods(4))
    assert all(r.node is not None for r in res)
    assert injector.log == [("fetch", FAULT_CORRUPT)]
    assert s.device_health.state == BREAKER_CLOSED
    # placements are on real nodes, not scrambled rows
    names = {r.node for r in res}
    assert names <= {f"n{i}" for i in range(4)}


def test_slow_device_is_absorbed_without_breaker_movement(injector):
    injector.arm("fence", kind=FAULT_SLOW, count=2, latency_s=0.02)
    s = _sched()
    res = s.schedule_cycle(_pods(4))
    assert all(r.node is not None for r in res)
    assert s.device_health.state == BREAKER_CLOSED
    assert list(s.device_health.transitions) == []


# --------------------------------------------------------- fault matrix


@pytest.mark.parametrize("site", list(SITES))
@pytest.mark.parametrize(
    "kind", [FAULT_TRANSIENT, FAULT_PERSISTENT, FAULT_CORRUPT, FAULT_SLOW]
)
def test_fault_matrix_smoke(injector, site, kind):
    """Sweep every injection point x fault kind once: whatever fires, the
    live scheduler neither loses a pod nor wedges, and it still schedules
    after the injector is disarmed."""
    injector.arm(site, kind=kind, count=1)
    # the scatter seam only runs on a dirty-ROW incremental upload, which
    # needs a dirty set <= N/4: a wider world plus a second wave after
    # the first commit drives it (the other sites fire on wave one)
    s = _sched(disable_preemption=True,
               n_nodes=32 if site == "scatter" else 4)
    pods = _pods(4)
    for p in pods:
        s.queue.add(p)
    for _ in range(3):
        s.run_once(timeout=0.05)
    if site == "scatter":
        wave2 = _pods(4, prefix="w2")
        pods = pods + wave2
        for p in wave2:
            s.queue.add(p)
        for _ in range(3):
            s.run_once(timeout=0.05)
    _no_pod_lost(s, pods)
    # corrupt arms only bite fetch-like sites; others fired exactly once
    if kind != FAULT_CORRUPT or site == "fetch":
        assert injector.log, f"{site}/{kind} never fired"
    injector.disarm()
    tail = _pods(2, prefix="tail")
    for p in tail:
        s.queue.add(p)
    placed = sum(s.run_once(timeout=0.05) for _ in range(4))
    assert placed >= 2, "scheduler wedged after the fault cleared"
    _no_pod_lost(s, pods + tail)


# ------------------------------------------------ chaos-harness integration


def test_chaosmonkey_device_storm_with_invariants():
    """The chaosmonkey shape over a device-fault storm: Disruptions arms
    the injector, the during-hook polls a race-safe liveness probe (a
    batch legitimately sits in flight mid-cycle, so per-pod accounting is
    only valid at quiescent points), teardown pins zero-pod-loss once the
    storm settles."""
    from kubernetes_tpu.runtime.chaos import Chaosmonkey, ChaosTest, Disruptions
    from kubernetes_tpu.runtime.cluster import LocalCluster

    s = _sched(n_nodes=4, batch_size=4, breaker_open_s=0.01)
    dis = Disruptions(LocalCluster())
    pods = _pods(12, prefix="storm")
    seen = []

    def probe():
        # the breaker never reports an out-of-vocabulary state, and the
        # scheduler thread keeps making progress (results only grow)
        assert s.device_health.state in ("closed", "open", "half_open")
        seen.append(len(s.results))

    def disruption():
        dis.device_lost("fence")
        for p in pods:
            s.queue.add(p)
        for _ in range(8):
            s.run_once(timeout=0.02)
        dis.clear_device_faults()
        time.sleep(s.config.breaker_open_s + 0.005)
        s.run_once(timeout=0.02)  # canary on an empty/queued poll

    cm = Chaosmonkey(disruption)
    cm.register(ChaosTest(
        "no-pod-lost",
        during=probe,
        teardown=lambda: _no_pod_lost(s, pods),
    ))
    try:
        cm.do(during_interval=0.01)
    finally:
        dis.clear_device_faults()
    assert seen, "during-hook never polled"
    # storm over: drain whatever is parked and confirm full completion
    s.queue.move_all_to_active()
    for _ in range(8):
        s.run_once(timeout=0.05)
    enc = s.cache.encoder
    bound = sum(
        1 for p in pods
        if enc.pods.get((p.namespace, p.name)) is not None
        and enc.pods[(p.namespace, p.name)].node_row >= 0
    )
    assert bound == 12
    # the breaker only closes when a post-recovery cycle actually probes
    # the device — push tail work to force the canary
    tail = _pods(2, prefix="post")
    for p in tail:
        s.queue.add(p)
    for _ in range(3):
        s.run_once(timeout=0.05)
    _no_pod_lost(s, tail)
    assert s.device_health.state == BREAKER_CLOSED


# ----------------------------------------------------- DeviceHealth unit


def test_device_health_backoff_is_jittered_bounded_deterministic():
    h1 = DeviceHealth(backoff_base_s=0.01, backoff_max_s=0.05,
                      backoff_jitter=0.5, seed=3)
    h2 = DeviceHealth(backoff_base_s=0.01, backoff_max_s=0.05,
                      backoff_jitter=0.5, seed=3)
    seq1 = [h1.backoff_s(a) for a in range(6)]
    seq2 = [h2.backoff_s(a) for a in range(6)]
    assert seq1 == seq2  # seeded determinism
    assert all(0.01 <= v <= 0.05 for v in seq1)  # jitter >= base, <= cap
    assert seq1[1] > seq1[0]  # exponential growth before the cap


def test_device_health_halfopen_grants_canary_once_cooled():
    now = [0.0]
    h = DeviceHealth(open_duration_s=1.0, clock=lambda: now[0])
    h.trip()
    assert not h.allow_device()
    now[0] = 0.5
    assert not h.allow_device()
    now[0] = 1.5
    assert h.allow_device()  # canary granted; state is half_open
    assert h.state == "half_open"
    h.record_success()
    assert h.state == BREAKER_CLOSED
    assert list(h.transitions) == [
        ("closed", "open"), ("open", "half_open"), ("half_open", "closed")
    ]


def test_pipelined_loop_degrades_classified_fence_fault(injector):
    """The pipelined double-buffer path shares the resilient fence: a
    persistent fault on batch k's fence degrades k to the CPU engine (and
    the breaker governs batch k+1's engine choice) — no pod lost, both
    waves placed."""
    injector.arm("fence", kind=FAULT_PERSISTENT)
    s = _sched(batch_size=4, pipeline_commit=True, breaker_open_s=60.0)
    pods = _pods(8, prefix="pl")
    for p in pods:
        s.queue.add(p)
    placed = 0
    for _ in range(6):
        placed += s.run_once(timeout=0.02)
    placed += s.flush_pipeline()
    assert placed == 8
    _no_pod_lost(s, pods)
    assert s.device_health.state == BREAKER_OPEN


def test_gang_members_survive_device_fault_via_plain_path(injector):
    """The gang launch has its own device path with no degraded engine: a
    classified fault there must feed the breaker and demote the members
    to the plain (retry/degrade-capable) path — popped gang members are
    never lost, and during an open breaker gangs schedule as plain pods
    (liveness over atomicity)."""
    injector.arm("fetch", kind=FAULT_PERSISTENT)
    s = _sched(n_nodes=4, batch_size=8, breaker_open_s=60.0,
               disable_preemption=True)
    gang = []
    for i in range(3):
        p = make_pod(f"g{i}", cpu="100m", mem="128Mi")
        p.labels[Scheduler.POD_GROUP_LABEL] = "team"
        p.labels[Scheduler.POD_GROUP_MIN_MEMBER] = "3"
        gang.append(p)
        s.queue.add(p)
    placed = 0
    for _ in range(4):
        placed += s.run_once(timeout=0.05)
    assert placed == 3, "gang members must place via the degraded path"
    _no_pod_lost(s, gang)
    assert s.device_health.state == BREAKER_OPEN
    assert s.recorder.events(reason="DeviceFault")


def test_validate_hosts_rejects_negative_corruption():
    """A winner value below -1 is wire corruption, not a FitError: it must
    raise the classified CorruptedFetchError (retry), never silently park
    the pod as unschedulable."""
    from kubernetes_tpu.codec.faults import CorruptedFetchError

    s = _sched()
    with pytest.raises(CorruptedFetchError):
        s._validate_hosts(np.array([-7, 0, 1, 2], np.int32), 4)
    # the legit range passes untouched
    out = s._validate_hosts(np.array([-1, 0, 1, 2], np.int32), 4)
    np.testing.assert_array_equal(out, [-1, 0, 1, 2])


def test_gang_fault_after_partial_commit_never_double_binds(monkeypatch):
    """schedule_gangs commits gang-by-gang: when a later gang's launch
    faults, members of already-committed gangs are bound and must NOT be
    re-scheduled (double bind / double capacity charge) — only the
    genuinely unplaced members recover through the plain path."""
    from kubernetes_tpu.models.gang import GangScheduler

    binds = []
    cache = SchedulerCache(SnapshotEncoder(TEST_DIMS))
    for i in range(4):
        cache.add_node(make_node(f"n{i}", cpu="8", mem="8Gi"))
    s = Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=lambda p, n: binds.append(p.name) or True,
        config=SchedulerConfig(batch_size=16, breaker_open_s=60.0,
                               disable_preemption=True),
    )
    pods = []
    for g, gname in enumerate(("alpha", "beta")):
        for i in range(3):
            p = make_pod(f"{gname}-{i}", cpu="100m", mem="128Mi")
            p.labels[Scheduler.POD_GROUP_LABEL] = gname
            p.labels[Scheduler.POD_GROUP_MIN_MEMBER] = "3"
            pods.append(p)
            s.queue.add(p)

    orig = GangScheduler.schedule_gangs

    def commit_first_then_lose_device(self, gangs):
        orig(self, gangs[:1])  # gang alpha commits (assume + bind) for real
        raise PersistentDeviceError("injected device-lost at gang launch")

    monkeypatch.setattr(
        GangScheduler, "schedule_gangs", commit_first_then_lose_device
    )
    placed = s.run_once(timeout=0.05)
    monkeypatch.setattr(GangScheduler, "schedule_gangs", orig)
    # alpha stayed bound exactly once; beta recovered via the degraded
    # plain path in the SAME cycle (persistent fault tripped the breaker)
    assert placed == 6
    assert sorted(binds) == sorted(p.name for p in pods), binds
    assert len(binds) == 6  # no double bind
    _no_pod_lost(s, pods)
    assert s.device_health.state == BREAKER_OPEN
    by_name = {r.pod.name: r.node for r in s.results}
    assert all(by_name.get(p.name) for p in pods)
