"""Differential parity: TPU kernels vs the object-level golden (cpuref).

The TPU-build analog of the reference's table-driven predicate/priority tests
plus randomized differential coverage (SURVEY.md section 4 testing lesson):
every (pod, node) cell of every predicate and every priority must agree with
the independent Python implementation.
"""

import numpy as np
import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.schema import (
    FilterConfig,
    PRED_INDEX,
    PREDICATE_ORDER,
    PRIO_INDEX,
    PRIORITY_ORDER,
)
from kubernetes_tpu.cpuref import CPUScheduler
from kubernetes_tpu.ops import filter_batch, score_batch

from fixtures import TEST_DIMS, make_node, make_pod, random_cluster, random_pending_pod

# Priorities computed through float *division/blending* chains: the reference
# does these in float64 and truncates to int; TPUs have no f64, so at exact
# integer boundaries (decimal fractions like 0.2 that are not binary-exact)
# the f32 result can floor one lower/higher.  Allowed drift: 1.  Everything
# else must match bit-for-bit.  (Tracked in PARITY.md.)
_FLOAT_BLEND_PRIORITIES = {
    "BalancedResourceAllocation",
    "SelectorSpreadPriority",
    "InterPodAffinityPriority",
    "RequestedToCapacityRatioPriority",
}
_CHECKED_PRIORITIES = list(PRIORITY_ORDER)


def build_encoder(nodes, pods, services):
    enc = SnapshotEncoder(TEST_DIMS)
    for n in nodes:
        enc.add_node(n)
    for p in pods:
        enc.add_pod(p)
    for ns, sel in services:
        enc.add_spread_selector(ns, sel)
    return enc


def run_device(enc, pending):
    cluster = enc.snapshot()
    batch = enc.encode_pods(pending)
    unsched = enc.interner.lookup("node.kubernetes.io/unschedulable")
    mask, per_pred = filter_batch(cluster, batch, FilterConfig(), max(unsched, 0))
    total, per_prio = score_batch(cluster, batch, zone_key_id=enc.getzone_key)
    return cluster, batch, np.asarray(mask), np.asarray(per_pred), np.asarray(total), np.asarray(per_prio)


def assert_parity(enc, nodes, pods, services, pending):
    golden = CPUScheduler(nodes, pods, services)
    _, _, mask, per_pred, _, per_prio = run_device(enc, pending)
    row = {name: enc.node_rows[name] for name in (n.name for n in nodes)}
    for b, pod in enumerate(pending):
        for node in nodes:
            want = golden.predicates(pod, node)
            r = row[node.name]
            for pname, ok in want.items():
                got = bool(per_pred[b, PRED_INDEX[pname], r])
                assert got == ok, (
                    f"pod={pod.name} node={node.name} predicate={pname}: "
                    f"device={got} golden={ok}"
                )
            assert bool(mask[b, r]) == all(want.values())
        prio = golden.priorities(pod)
        for pname in _CHECKED_PRIORITIES:
            tol = 1 if pname in _FLOAT_BLEND_PRIORITIES else 0
            for node in nodes:
                got = per_prio[b, PRIO_INDEX[pname], row[node.name]]
                want_score = prio[pname][node.name]
                assert abs(got - want_score) <= tol, (
                    f"pod={pod.name} node={node.name} priority={pname}: "
                    f"device={got} golden={want_score}"
                )


def test_basic_resources_fit():
    nodes = [make_node("n1", cpu="1", mem="1Gi"), make_node("n2", cpu="4", mem="8Gi")]
    pods = [make_pod("existing", cpu="500m", mem="512Mi", node_name="n1")]
    pending = [make_pod("p", cpu="600m", mem="256Mi")]
    enc = build_encoder(nodes, pods, [])
    assert_parity(enc, nodes, pods, [], pending)


def test_taints_tolerations():
    nodes = [
        make_node("n1", taints=[{"key": "dedicated", "value": "gpu", "effect": "NoSchedule"}]),
        make_node("n2", taints=[{"key": "x", "effect": "PreferNoSchedule"}]),
        make_node("n3"),
    ]
    pending = [
        make_pod("p1"),
        make_pod("p2", tolerations=[{"key": "dedicated", "operator": "Equal", "value": "gpu", "effect": "NoSchedule"}]),
        make_pod("p3", tolerations=[{"operator": "Exists"}]),
    ]
    enc = build_encoder(nodes, [], [])
    assert_parity(enc, nodes, [], [], pending)


def test_node_selector_and_affinity():
    nodes = [
        make_node("n1", labels={"disk": "ssd", "num": "5"}),
        make_node("n2", labels={"disk": "hdd"}),
        make_node("n3"),
    ]
    pending = [
        make_pod("p1", node_selector={"disk": "ssd"}),
        make_pod(
            "p2",
            affinity={
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {"matchExpressions": [{"key": "disk", "operator": "In", "values": ["ssd", "nvme"]}]},
                            {"matchExpressions": [{"key": "num", "operator": "Gt", "values": ["3"]}]},
                        ]
                    }
                }
            },
        ),
        make_pod(
            "p3",
            affinity={
                "nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {"matchFields": [{"key": "metadata.name", "operator": "In", "values": ["n3"]}]}
                        ]
                    }
                }
            },
        ),
    ]
    enc = build_encoder(nodes, [], [])
    assert_parity(enc, nodes, [], [], pending)


def test_host_ports():
    nodes = [make_node("n1"), make_node("n2")]
    pods = [
        make_pod("e1", node_name="n1", ports=[{"hostPort": 80, "protocol": "TCP"}]),
        make_pod("e2", node_name="n2", ports=[{"hostPort": 80, "protocol": "TCP", "hostIP": "10.0.0.1"}]),
    ]
    pending = [
        make_pod("p1", ports=[{"hostPort": 80, "protocol": "TCP"}]),
        make_pod("p2", ports=[{"hostPort": 80, "protocol": "UDP"}]),
        make_pod("p3", ports=[{"hostPort": 80, "protocol": "TCP", "hostIP": "10.0.0.2"}]),
    ]
    enc = build_encoder(nodes, pods, [])
    assert_parity(enc, nodes, pods, [], pending)


def test_inter_pod_affinity_required():
    zone = "failure-domain.beta.kubernetes.io/zone"
    nodes = [
        make_node("n1", labels={zone: "z1"}),
        make_node("n2", labels={zone: "z1"}),
        make_node("n3", labels={zone: "z2"}),
    ]
    pods = [make_pod("web", labels={"app": "web"}, node_name="n1")]
    pending = [
        make_pod(
            "want-near",
            labels={"app": "cache"},
            affinity={
                "podAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {"labelSelector": {"matchLabels": {"app": "web"}}, "topologyKey": zone}
                    ]
                }
            },
        ),
        make_pod(
            "want-away",
            labels={"app": "web"},
            affinity={
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {"labelSelector": {"matchLabels": {"app": "web"}}, "topologyKey": zone}
                    ]
                }
            },
        ),
        make_pod(
            "bootstrap",
            labels={"app": "new"},
            affinity={
                "podAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {"labelSelector": {"matchLabels": {"app": "new"}}, "topologyKey": zone}
                    ]
                }
            },
        ),
    ]
    enc = build_encoder(nodes, pods, [])
    assert_parity(enc, nodes, pods, [], pending)


def test_existing_anti_affinity_blocks():
    host = "kubernetes.io/hostname"
    nodes = [make_node("n1"), make_node("n2")]
    pods = [
        make_pod(
            "lonely",
            labels={"app": "lonely"},
            node_name="n1",
            affinity={
                "podAntiAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {"labelSelector": {"matchLabels": {"app": "web"}}, "topologyKey": host}
                    ]
                }
            },
        )
    ]
    pending = [make_pod("w", labels={"app": "web"}), make_pod("other", labels={"app": "db"})]
    enc = build_encoder(nodes, pods, [])
    assert_parity(enc, nodes, pods, [], pending)


def test_spreading_and_scores():
    zone = "failure-domain.beta.kubernetes.io/zone"
    nodes = [
        make_node("n1", labels={zone: "z1"}),
        make_node("n2", labels={zone: "z1"}),
        make_node("n3", labels={zone: "z2"}),
    ]
    pods = [
        make_pod("a1", labels={"app": "a"}, node_name="n1"),
        make_pod("a2", labels={"app": "a"}, node_name="n1"),
        make_pod("a3", labels={"app": "a"}, node_name="n3"),
    ]
    services = [("default", {"app": "a"})]
    pending = [make_pod("a4", labels={"app": "a"})]
    enc = build_encoder(nodes, pods, services)
    assert_parity(enc, nodes, pods, services, pending)


def test_prefer_avoid_and_images():
    ann = (
        '{"preferAvoidPods": [{"podSignature": {"podController": '
        '{"kind": "ReplicationController", "uid": "rc-1"}}}]}'
    )
    nodes = [
        make_node(
            "n1",
            annotations={"scheduler.alpha.kubernetes.io/preferAvoidPods": ann},
            images=[{"names": ["img-big"], "sizeBytes": 900 * 1024 * 1024}],
        ),
        make_node("n2", images=[{"names": ["img-big"], "sizeBytes": 900 * 1024 * 1024}]),
        make_node("n3"),
    ]
    pending = [
        make_pod("p1", owner=("ReplicationController", "rc-1"), images=["img-big"]),
        make_pod("p2", owner=("Deployment", "rc-1")),
    ]
    enc = build_encoder(nodes, [], [])
    assert_parity(enc, nodes, [], [], pending)


def test_unschedulable_and_conditions():
    nodes = [
        make_node("n1", unschedulable=True),
        make_node("n2", conditions=[{"type": "Ready", "status": "False"}]),
        make_node("n3", conditions=[{"type": "Ready", "status": "True"}, {"type": "MemoryPressure", "status": "True"}]),
        make_node("n4"),
    ]
    pending = [
        make_pod("best-effort"),
        make_pod("burstable", cpu="100m"),
        make_pod(
            "tolerates-unsched",
            tolerations=[{"key": "node.kubernetes.io/unschedulable", "operator": "Exists"}],
        ),
    ]
    enc = build_encoder(nodes, [], [])
    assert_parity(enc, nodes, [], [], pending)


def test_disk_conflict_and_vol_counts():
    nodes = [make_node("n1"), make_node("n2")]
    pods = [
        make_pod(
            "e1",
            node_name="n1",
            volumes=[{"gcePersistentDisk": {"pdName": "disk-a"}}],
        )
    ]
    pending = [
        make_pod("p1", volumes=[{"gcePersistentDisk": {"pdName": "disk-a"}}]),
        make_pod("p2", volumes=[{"gcePersistentDisk": {"pdName": "disk-b"}}]),
    ]
    enc = build_encoder(nodes, pods, [])
    assert_parity(enc, nodes, pods, [], pending)


@pytest.mark.parametrize("seed", range(6))
def test_randomized_differential(seed):
    rng = np.random.default_rng(1000 + seed)
    nodes, pods, services = random_cluster(rng, n_nodes=10, n_pods=24)
    pending = [random_pending_pod(rng, i) for i in range(8)]
    enc = build_encoder(nodes, pods, services)
    assert_parity(enc, nodes, pods, services, pending)
