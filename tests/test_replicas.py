"""Queue-sharded scheduler replicas + optimistic conflict reconciler
(ISSUE 14).

Covers: stable hash-shard pops (add/delete/readd stability, guards
spanning shards), the reconciler edge matrix (zero-conflict fast path
allocation-free, all-N-conflict admitting exactly the sequenced winner,
DRF tiebreak ordering, quota vetoes, conflict against a DEGRADED
replica's CPU-adapter cycle), the per-scheduler observability installs
with the explicit process aggregate (two-replica pin), the new metric
families under the strict /metrics parser, GET /debug/replicas on both
servers, heartbeat fields, ledger replica+seq replay, and the
invariant-checker-clean N-replica overload storm (chaos marker).
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.codec.encoder import SnapshotEncoder
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.queue import PriorityQueue
from kubernetes_tpu.runtime.replicas import SchedulerReplicaSet
from kubernetes_tpu.runtime.scheduler import SchedulerConfig
from kubernetes_tpu.utils import metrics as m

from fixtures import TEST_DIMS, make_node, make_pod

pytestmark = pytest.mark.replicas


def _config(**kw) -> SchedulerConfig:
    base = dict(
        batch_size=8,
        batch_window_s=0.0,
        engine="sequential",
        disable_preemption=True,
        telemetry=True,
        quality_top_k=0,   # keep the tiny test launches lean
    )
    base.update(kw)
    return SchedulerConfig(**base)


def _replica_set(n=2, nodes=4, cpu="8", **cfg_kw) -> SchedulerReplicaSet:
    rs = SchedulerReplicaSet(
        replicas=n,
        cache=SchedulerCache(SnapshotEncoder(TEST_DIMS)),
        config=_config(**cfg_kw),
    )
    for i in range(nodes):
        rs.cache.add_node(make_node(f"n{i}", cpu=cpu, mem="32Gi"))
    return rs


def _drive(rs: SchedulerReplicaSet, rounds=40) -> None:
    """Deterministic synchronous drive: round-robin run_once."""
    for _ in range(rounds):
        for s in rs.schedulers:
            s.run_once(timeout=0.0)
        if not rs.queue.has_schedulable() and not any(
            s.pipeline_pending for s in rs.schedulers
        ):
            break
    for s in rs.schedulers:
        s.flush_pipeline()


# ------------------------------------------------------- queue sharding


def test_shard_of_is_stable_and_deterministic():
    pods = [make_pod(f"p{i}", namespace=f"ns{i % 3}") for i in range(64)]
    for of in (1, 2, 4, 8):
        first = [PriorityQueue.shard_of(p, of) for p in pods]
        again = [PriorityQueue.shard_of(p, of) for p in pods]
        assert first == again
        assert all(0 <= s < of for s in first)
    # key-tuple form agrees with the pod form
    for p in pods:
        assert PriorityQueue.shard_of((p.namespace, p.name), 4) == (
            PriorityQueue.shard_of(p, 4)
        )


def test_shard_pops_disjoint_exhaustive_and_stable_under_readd():
    q = PriorityQueue()
    pods = [make_pod(f"p{i}", priority=i % 3) for i in range(40)]
    for p in pods:
        q.add(p)
    by_shard = {
        i: q.pop_batch(100, 0.0, 0.0, shard=i, of=4) for i in range(4)
    }
    got = sorted(p.name for b in by_shard.values() for p in b)
    assert got == sorted(p.name for p in pods)
    for i, batch in by_shard.items():
        for p in batch:
            assert PriorityQueue.shard_of(p, 4) == i
    # readd returns to the OWNER shard; other shards never see it
    victim = by_shard[2][0]
    q.readd(victim)
    for i in (0, 1, 3):
        assert q.pop_batch(10, 0.0, 0.0, shard=i) == []
    back = q.pop_batch(10, 0.0, 0.0, shard=2)
    assert [p.name for p in back] == [victim.name]
    # delete + re-add keeps the shard too
    q.add(victim)
    q.delete(victim)
    q.add(victim)
    assert [p.name for p in q.pop_batch(10, 0.0, 0.0, shard=2)] == [
        victim.name
    ]


def test_global_pop_order_unchanged_by_sharding():
    """pop() without a shard arg pops the GLOBAL priority-FIFO best
    across shard heaps — identical order to an unsharded queue."""
    ref, sharded = PriorityQueue(), PriorityQueue(shards=4)
    pods = [make_pod(f"p{i}", priority=(i * 7) % 5) for i in range(30)]
    for p in pods:
        ref.add(p)
        sharded.add(p)
    ref_order = [ref.pop(0.0).name for _ in range(30)]
    sharded_order = [sharded.pop(0.0).name for _ in range(30)]
    assert ref_order == sharded_order


def test_shed_guard_spans_shards():
    """The at-capacity shed candidate scan sees EVERY shard's entries:
    a high-priority arrival on shard A may evict the lowest-priority
    pod even when it lives on shard B."""
    q = PriorityQueue(capacity=4, shards=4)
    low = [make_pod(f"low{i}", priority=0) for i in range(4)]
    for p in low:
        q.add(p)
    shed = []
    q.on_shed = lambda pod, reason: shed.append((pod.name, reason))
    vip = make_pod("vip", priority=100)
    q.add(vip)
    assert len(q) == 4
    assert shed and shed[0][0].startswith("low")
    # the vip is poppable from its own shard
    s = PriorityQueue.shard_of(vip, 4)
    assert any(
        p.name == "vip" for p in q.pop_batch(10, 0.0, 0.0, shard=s)
    )


# ------------------------------------------------- reconciler edge matrix


def test_zero_conflict_fast_path_is_allocation_free():
    rs = _replica_set(n=2)
    r0 = rs.schedulers[0]
    for i in range(4):
        rs.queue.add(make_pod(f"p{i}", cpu="100m"))
    # no sibling interleaves: every commit must ride the generation
    # fence — neither the jitted kernel nor the numpy twin may run
    def _boom(*a, **kw):
        raise AssertionError("fast path must not reach the scan")

    rs.reconciler._kernel = _boom
    rs.reconciler._admit_np = _boom
    _drive(rs)
    assert rs.placed_total == 4
    stats = rs.reconciler.stats()
    assert stats["kernel_calls"] == 0
    assert stats["scans_total"] == 0
    assert stats["fast_path_total"] >= 1
    assert r0.conflicts_total == 0


def test_all_n_conflict_admits_exactly_the_sequenced_winner():
    rs = _replica_set(n=3, nodes=1, cpu="4")
    r0, r1, r2 = rs.schedulers
    # node headroom fits exactly ONE 3-cpu pod; all three replicas
    # dispatch against the SAME snapshot generation
    pods = [
        make_pod(f"c{i}", cpu="3", namespace=f"t{i}") for i in range(3)
    ]
    infs = [
        s._encode_and_dispatch([p]) for s, p in zip(rs.schedulers, pods)
    ]
    assert len({inf.generation for inf in infs}) == 1
    staged = [
        s._commit_state(inf) for s, inf in zip(rs.schedulers, infs)
    ]
    assert [len(st.winners) for st in staged] == [1, 0, 0]
    assert [len(st.race_lost) for st in staged] == [0, 1, 1]
    for s, st in zip(rs.schedulers, staged):
        s._commit_tail(st)
    # losers went back to their OWNER shards, shed-exempt
    assert len(rs.queue) == 2
    assert rs.reconciler.conflicts_total == 2
    # commit sequence stamped in dispatch order of the commits
    assert [inf.commit_seq for inf in infs] == [1, 2, 3]
    assert rs.invariant_violations_total() == 0


def test_drf_tiebreak_prefers_smaller_dominant_share():
    rs = _replica_set(n=2, nodes=1, cpu="8")
    r0, r1 = rs.schedulers
    # tenant "hog" already holds committed capacity; tenant "tiny" none
    seed = make_pod("seed", cpu="2", namespace="hog", node_name="n0")
    rs.cache.add_pod(seed)
    # the ENGINE sees headroom 6 and approves BOTH 3-cpu contenders;
    # a sibling commit then shrinks live headroom to 4.5 — room for
    # one.  Batch order puts hog FIRST, so only the DRF order can make
    # tiny win the sequenced admission.
    contenders = [
        make_pod("hog-pod", cpu="3", namespace="hog"),
        make_pod("tiny-pod", cpu="3", namespace="tiny"),
    ]
    inf = r0._encode_and_dispatch(contenders)
    bump = make_pod("bump", cpu="1500m", namespace="zz", node_name="n0")
    rs.cache.add_pod(bump)
    st = r0._commit_state(inf)
    winners = [w[1].name for w in st.winners]
    losers = [p.name for _, p in st.race_lost]
    assert winners == ["tiny-pod"], (winners, losers)
    assert losers == ["hog-pod"]
    r0._commit_tail(st)
    assert rs.reconciler.stats()["scans_total"] == 1


def test_quota_veto_parks_unschedulable():
    rs = _replica_set(
        n=2, nodes=2, cpu="8",
        namespace_quotas={"capped": {"cpu": "1"}},
    )
    r0 = rs.schedulers[0]
    pods = [
        make_pod("q1", cpu="900m", namespace="capped"),
        make_pod("q2", cpu="900m", namespace="capped"),
        make_pod("free", cpu="900m", namespace="open"),
    ]
    inf = r0._encode_and_dispatch(pods)
    st = r0._commit_state(inf)
    names = sorted(w[1].name for w in st.winners)
    assert names == ["free", "q1"], names
    assert [p.name for _, p in st.quota_lost] == ["q2"]
    assert st.race_lost == []
    r0._commit_tail(st)
    # the quota loser PARKED (unschedulable w/ backoff), not active
    assert len(rs.queue) == 1
    assert rs.queue.active_depth() == 0
    assert rs.reconciler.quota_vetoes_total == 1
    evs = [
        e for e in r0.recorder.events() if e.reason == "QuotaExceeded"
    ]
    assert evs and evs[0].name == "q2"


def test_stale_fence_requeues_port_carrying_winner():
    """A winner carrying a constraint the scan cannot re-validate
    (host ports here) must NOT commit optimistically across a stale
    generation fence: it requeues to its owner shard and places on the
    next, fresh dispatch.  Lean pods in the same cycle still admit."""
    rs = _replica_set(n=2, nodes=2, cpu="8")
    r0 = rs.schedulers[0]
    porty = make_pod("porty", cpu="100m", ports=[{"containerPort": 80,
                                                  "hostPort": 8080}])
    lean = make_pod("lean", cpu="100m", namespace="t2")
    inf = r0._encode_and_dispatch([porty, lean])
    # a sibling commit bumps the generation -> stale fence
    bump = make_pod("bump", cpu="100m", namespace="zz", node_name="n0")
    rs.cache.add_pod(bump)
    st = r0._commit_state(inf)
    assert [w[1].name for w in st.winners] == ["lean"]
    assert [p.name for _, p in st.race_lost] == ["porty"]
    r0._commit_tail(st)
    assert rs.reconciler.strict_requeues_total == 1
    # the requeued pod is ACTIVE on its owner shard and places cleanly
    # on a fresh cycle (no interleave this time -> fast path)
    shard = PriorityQueue.shard_of(porty, 2)
    repl = rs.schedulers[shard]
    got = rs.queue.pop_batch(4, 0.0, 0.0, shard=shard, of=2)
    assert [p.name for p in got] == ["porty"]
    inf2 = repl._encode_and_dispatch(got)
    st2 = repl._commit_state(inf2)
    assert [w[1].name for w in st2.winners] == ["porty"]
    repl._commit_tail(st2)
    assert rs.invariant_violations_total() == 0


@pytest.mark.chaos
def test_conflict_against_degraded_replica_cpu_adapter_cycle():
    """A replica whose breaker is open serves its cycle from the CPU
    adapter; the reconciler still sequences its commit — via the numpy
    twin — and requeues the race loser."""
    rs = _replica_set(n=2, nodes=1, cpu="4")
    r0, r1 = rs.schedulers
    # trip replica 1's breaker: its cycles degrade to the CPU engine
    from kubernetes_tpu.codec.faults import FAULT_PERSISTENT

    r1.device_health.record_failure(FAULT_PERSISTENT)
    assert not r1.device_health.device_available
    pa = make_pod("dev-pod", cpu="3", namespace="ta")
    pb = make_pod("cpu-pod", cpu="3", namespace="tb")
    inf0 = r0._encode_and_dispatch([pa])
    inf1 = r1._encode_and_dispatch([pb])
    assert inf1.degraded
    kernel_calls0 = rs.reconciler.kernel_calls
    st0 = r0._commit_state(inf0)
    st1 = r1._commit_state(inf1)
    assert len(st0.winners) == 1
    assert [p.name for _, p in st1.race_lost] == ["cpu-pod"]
    # the degraded commit used the numpy twin, not a device launch
    assert rs.reconciler.kernel_calls == kernel_calls0
    r0._commit_tail(st0)
    r1._commit_tail(st1)
    assert rs.invariant_violations_total() == 0


@pytest.mark.chaos
def test_replica_overload_storm_invariants_clean():
    """N replicas + a bounded shedding queue + a multi-tenant burst
    over capacity: conservation holds by construction — offered ==
    placed + shed + still-queued, zero invariant violations, and no
    popped pod is lost at drain."""
    rs = SchedulerReplicaSet(
        replicas=3,
        cache=SchedulerCache(SnapshotEncoder(TEST_DIMS)),
        queue=PriorityQueue(capacity=64, shards=3),
        config=_config(batch_size=16, queue_capacity=64),
    )
    for i in range(8):
        rs.cache.add_node(make_node(f"n{i}", cpu="16", mem="32Gi"))
    offered = 160
    for i in range(offered):
        rs.queue.add(
            make_pod(f"s{i}", cpu="50m", namespace=f"tenant{i % 4}",
                     priority=i % 3)
        )
    shed_on_admit = rs.queue.shed_total
    _drive(rs, rounds=120)
    placed = rs.placed_total
    shed = rs.queue.shed_total
    left = len(rs.queue)
    assert placed + shed + left >= offered - 0  # nothing vanished
    assert placed > 0
    assert rs.invariant_violations_total() == 0
    assert rs.assert_drained()
    # every tenant that offered pods got SOME placements (DRF ordering
    # + hash shards cannot starve a namespace wholesale)
    per_tenant = {f"tenant{t}": 0 for t in range(4)}
    for s in rs.schedulers:
        for r in s.results:
            if r.node is not None:
                per_tenant[r.pod.namespace] += 1
    assert all(v > 0 for v in per_tenant.values()), per_tenant
    del shed_on_admit


# ------------------------------------ singleton installs + aggregate


def test_two_replica_installs_keep_primary_default_and_aggregate():
    from kubernetes_tpu.runtime import perfobs as perfobs_mod
    from kubernetes_tpu.runtime import quality as quality_mod
    from kubernetes_tpu.runtime import telemetry as telemetry_mod

    rs = _replica_set(n=2, quality_top_k=3)
    r0, r1 = rs.schedulers
    # the process DEFAULT is replica 0's instance (not last-writer r1)
    assert telemetry_mod.get_default() is r0.telemetry
    assert perfobs_mod.get_default() is r0.perfobs
    assert quality_mod.get_default() is r0.quality
    # ...and the explicit aggregate holds BOTH replicas' instances
    assert telemetry_mod.replica_instances()[0] is r0.telemetry
    assert telemetry_mod.replica_instances()[1] is r1.telemetry
    assert r0.telemetry is not r1.telemetry
    assert perfobs_mod.replica_instances()[1] is r1.perfobs
    assert quality_mod.replica_instances()[1] is r1.quality
    # both replicas retire spans into the ONE process flight recorder,
    # tagged with their replica id
    for i in range(8):
        rs.queue.add(make_pod(f"p{i}", cpu="100m"))
    _drive(rs)
    assert rs.placed_total == 8
    # the ring is the PROCESS recorder (shared across the suite), so
    # other tests' replicas may appear too — this set's replica 0 must
    replicas_seen = {
        sp.attrs.get("replica")
        for sp in r0.flight_recorder.spans()
        if sp.attrs.get("replica") is not None
    }
    assert 0 in replicas_seen
    # per-replica cycles land in each replica's OWN observatory — no
    # misattribution to the surviving default
    assert r0.perfobs.summary()["cycles"] >= 1
    if r1._outcome_totals["placed"] or r1._outcome_totals["unschedulable"]:
        assert r1.perfobs.summary()["cycles"] >= 1


def test_debug_replicas_payload_and_metric_families():
    from test_metrics_format import parse_exposition

    rs = _replica_set(n=2, nodes=1, cpu="4")
    # manufacture one conflict so the families have samples
    pa = make_pod("ma", cpu="3", namespace="ta")
    pb = make_pod("mb", cpu="3", namespace="tb")
    inf0 = rs.schedulers[0]._encode_and_dispatch([pa])
    inf1 = rs.schedulers[1]._encode_and_dispatch([pb])
    for s, inf in zip(rs.schedulers, (inf0, inf1)):
        s._commit_tail(s._commit_state(inf))
    from kubernetes_tpu.runtime import reconciler as rmod

    payload = rmod.debug_payload()
    assert payload["replicas"] >= 2
    assert payload["reconciler"]["conflicts_total"] >= 1
    assert "ta" in payload["tenants"] or "tb" in payload["tenants"]
    per = payload["per_replica"]
    assert per["0"]["placed"] >= 1
    assert per["1"]["conflicts"] >= 1
    json.dumps(payload)  # JSON-serializable end to end
    # strict exposition: the three new families parse with the right
    # types and labels
    fams = parse_exposition(m.REGISTRY.expose())
    assert fams["scheduler_replicas"]["type"] == "gauge"
    assert fams["scheduler_replicas"]["samples"][0][2] >= 2
    conf = fams["scheduler_replica_conflicts_total"]
    assert conf["type"] == "counter"
    assert any(
        s[1].get("replica") == "1" and s[2] >= 1 for s in conf["samples"]
    )
    req = fams["scheduler_replica_requeued_pods_total"]
    assert req["type"] == "counter"
    assert req["samples"][0][2] >= 1


def test_debug_replicas_served_on_both_servers():
    from kubernetes_tpu.runtime.health import HealthServer
    from kubernetes_tpu.apiserver.server import APIServer
    from kubernetes_tpu.runtime.ledger import DEBUG_ENDPOINTS

    assert "/debug/replicas" in DEBUG_ENDPOINTS
    rs = _replica_set(n=2)
    del rs  # registered as a side effect; the endpoint reads the registry
    hs = HealthServer(host="127.0.0.1", port=0).start()
    try:
        host, port = hs.address
        body = urllib.request.urlopen(
            f"http://{host}:{port}/debug/replicas?limit=2", timeout=5
        ).read()
        payload = json.loads(body)
        assert "per_replica" in payload and "reconciler" in payload
        idx = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/debug/", timeout=5
        ).read())
        assert "/debug/replicas" in idx["endpoints"]
    finally:
        hs.stop()
    api = APIServer(host="127.0.0.1", port=0).start()
    try:
        host, port = api.address
        payload = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/debug/replicas", timeout=5
        ).read())
        assert "per_replica" in payload
    finally:
        api.stop()


def test_heartbeat_line_carries_replica_fields():
    from kubernetes_tpu.utils import klog

    rs = _replica_set(n=2, heartbeat_s=0.01)
    for i in range(4):
        rs.queue.add(make_pod(f"h{i}", cpu="100m"))
    records = []
    orig = klog.infof
    try:
        klog.infof = lambda fmt, *a: records.append(fmt % a if a else fmt)
        time.sleep(0.02)
        _drive(rs)
        time.sleep(0.02)
        rs.schedulers[0].run_once(timeout=0.0)
    finally:
        klog.infof = orig
    beats = [r for r in records if r.startswith("heartbeat:")]
    assert beats, "no heartbeat line"
    assert "replicas=2" in beats[-1]
    assert "conflicts=" in beats[-1]


# ------------------------------------------------------ ledger replay


def test_ledger_records_replica_seq_and_replays_bit_identical(tmp_path):
    from kubernetes_tpu.runtime import ledger as ledger_mod

    path = str(tmp_path / "replicas.ledger")
    rs = SchedulerReplicaSet(
        replicas=2,
        cache=SchedulerCache(SnapshotEncoder(TEST_DIMS)),
        config=_config(decision_ledger=True),
        ledger=ledger_mod.DecisionLedger(path=path),
    )
    for i in range(2):
        rs.cache.add_node(make_node(f"n{i}", cpu="8", mem="32Gi"))
    for i in range(24):
        rs.queue.add(make_pod(f"L{i}", cpu="100m", namespace=f"t{i % 2}"))
    _drive(rs)
    assert rs.placed_total == 24
    rs.primary.ledger.flush(30.0)
    header, records = ledger_mod.read_ledger(path)
    assert records, "no recorded cycles"
    replicas_seen = {rec.get("replica") for rec in records}
    assert replicas_seen <= {0, 1} and replicas_seen
    seqs = [rec.get("seq") for rec in records if rec.get("seq")]
    assert len(seqs) == len(set(seqs)), "commit sequence must be unique"
    # every replica's every cycle replays to bit-identical winners
    out = ledger_mod.replay(path, cluster_stats=False)
    assert out["bit_identical"], out
    assert out["cycles"] == len(records)
    # the /debug/decisions ring carries the replica tag too
    entries = rs.primary.ledger.decisions()
    assert any(e.get("replica") is not None for e in entries)


# --------------------------------------------------- threaded smoke


def test_threaded_replicas_drain_and_config_plumbing():
    from kubernetes_tpu.config.types import KubeSchedulerConfiguration

    cc = KubeSchedulerConfiguration.from_dict({
        "replicas": 2,
        "namespaceQuotas": {"capped": {"cpu": "1"}},
    })
    assert cc.replicas == 2
    cfg = SchedulerConfig.from_component_config(cc)
    assert cfg.replicas == 2
    assert cfg.namespace_quotas == {"capped": {"cpu": "1"}}
    rs = SchedulerReplicaSet(
        replicas=2,
        cache=SchedulerCache(SnapshotEncoder(TEST_DIMS)),
        config=_config(batch_size=16),
    )
    for i in range(4):
        rs.cache.add_node(make_node(f"n{i}", cpu="16", mem="32Gi"))
    for i in range(64):
        rs.queue.add(make_pod(f"T{i}", cpu="50m"))
    placed = rs.run_until_drained(budget_s=60)
    rs.stop()
    assert rs.placed_total == 64, rs.summary()
    assert rs.assert_drained()
    assert placed >= 0
    # guards: replicas exclude mesh sharding + per-pod commit
    with pytest.raises(ValueError):
        SchedulerReplicaSet(replicas=2, config=_config(shard_devices=2))
    with pytest.raises(ValueError):
        SchedulerReplicaSet(
            replicas=2, config=_config(batched_commit=False)
        )


def test_replicas_with_megacycles():
    """Replicas dispatch megacycles against the shared snapshot: the
    chained-window fence keeps sub-batches on the fast path when no
    sibling interleaves, and conservation holds either way."""
    rs = SchedulerReplicaSet(
        replicas=2,
        cache=SchedulerCache(SnapshotEncoder(TEST_DIMS)),
        config=_config(batch_size=8, megacycle_batches=2),
    )
    for i in range(4):
        rs.cache.add_node(make_node(f"n{i}", cpu="16", mem="32Gi"))
    for i in range(64):
        rs.queue.add(make_pod(f"M{i}", cpu="50m"))
    _drive(rs, rounds=80)
    assert rs.placed_total == 64, rs.summary()
    assert rs.invariant_violations_total() == 0
    assert rs.assert_drained()
    assert rs.primary.megacycles_total + rs.schedulers[1].megacycles_total > 0
