"""Preemption what-if parity: device level-sweep vs golden per-pod reprieve."""

import numpy as np
import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.schema import FilterConfig
from kubernetes_tpu.cpuref import CPUScheduler
from kubernetes_tpu.models.preemption import (
    dense_start_ranks,
    preempt_one,
    preemption_candidates,
    sorted_victim_slots,
)
from kubernetes_tpu.ops import filter_batch
from kubernetes_tpu.ops.predicates import required_affinity_ok

from fixtures import TEST_DIMS, make_node, make_pod


def run_device_preempt(nodes, existing, preemptor, pdbs=(), pvs=(), pvcs=()):
    enc = SnapshotEncoder(TEST_DIMS)
    for pv in pvs:
        enc.add_pv(pv)
    for pvc in pvcs:
        enc.add_pvc(pvc)
    for n in nodes:
        enc.add_node(n)
    for p in existing:
        enc.add_pod(p)
    cluster = enc.snapshot()
    batch = enc.encode_pods([preemptor])
    _, per_pred = filter_batch(cluster, batch, FilterConfig(), 0)
    aff_ok = required_affinity_ok(cluster, batch)
    cands = preemption_candidates(
        np.asarray(per_pred), np.asarray(cluster.valid), np.asarray(aff_ok)
    )[0]
    arena = enc.pods_snapshot()
    violating = np.zeros(len(arena.node), bool)
    for rec in enc.pods.values():
        if rec.pod is not None and rec.node_row >= 0:
            violating[rec.m] = any(
                pdb.matches(rec.pod) and pdb.disruptions_allowed <= 0 for pdb in pdbs
            )
    slots = sorted_victim_slots(
        arena.priority, arena.valid, arena.node, preemptor.spec.priority,
        violating, arena.start,
    )
    pod_req_ext, requested_ext, allocatable_ext, pods_ext = enc.preemption_arrays(
        preemptor
    )
    # the identity-deduped volume-credit path (pick_preemption_node's):
    # per-pod volume-count columns zeroed, vid tables drive the credit
    vol_tables = enc.victim_volume_tables(slots)
    pods_ext = pods_ext.copy()
    pods_ext[:, requested_ext.shape[1] - vol_tables[4].shape[1]:] = 0.0
    res = preempt_one(
        requested_ext,
        allocatable_ext,
        pod_req_ext,
        cands,
        arena.node,
        arena.priority,
        pods_ext,
        violating,
        dense_start_ranks(arena.start),
        slots,
        vol_tables=vol_tables,
        has_vols=True,
    )
    node_row = int(res.node)
    row_names = {row: name for name, row in enc.node_rows.items()}
    victims = {
        arena.keys[m] for m in np.nonzero(np.asarray(res.victim_mask))[0]
    }
    return (row_names[node_row] if node_row >= 0 else None), victims


def test_preempt_basic():
    nodes = [make_node("n1", cpu="1", mem="4Gi"), make_node("n2", cpu="1", mem="4Gi")]
    existing = [
        make_pod("low-a", cpu="600m", node_name="n1", priority=1),
        make_pod("low-b", cpu="600m", node_name="n2", priority=2),
    ]
    preemptor = make_pod("high", cpu="800m", priority=100)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    golden = CPUScheduler(nodes, existing)
    want_node, want_victims = golden.preempt(preemptor)
    assert got_node == want_node
    assert got_victims == want_victims
    assert got_node == "n1"  # victim priority 1 < 2


def test_preempt_reprieve_keeps_high_priority():
    # node has two victims; evicting only the lower one suffices
    nodes = [make_node("n1", cpu="2", mem="4Gi")]
    existing = [
        make_pod("keep", cpu="500m", node_name="n1", priority=50),
        make_pod("evict", cpu="1", node_name="n1", priority=1),
    ]
    preemptor = make_pod("high", cpu="1400m", priority=100)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    golden = CPUScheduler(nodes, existing)
    want_node, want_victims = golden.preempt(preemptor)
    assert got_node == want_node == "n1"
    assert got_victims == want_victims == {("default", "evict")}


def test_preempt_impossible():
    # higher-priority occupants: nothing to evict
    nodes = [make_node("n1", cpu="1", mem="4Gi")]
    existing = [make_pod("top", cpu="900m", node_name="n1", priority=1000)]
    preemptor = make_pod("mid", cpu="500m", priority=100)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    golden = CPUScheduler(nodes, existing)
    want_node, _ = golden.preempt(preemptor)
    assert got_node is None and want_node is None
    assert got_victims == set()


def test_preempt_unresolvable_node_skipped():
    # n1 requires a selector the pod lacks: preemption can't help there
    nodes = [
        make_node("n1", cpu="4", mem="8Gi", labels={"disk": "ssd"}),
        make_node("n2", cpu="1", mem="4Gi"),
    ]
    existing = [make_pod("low", cpu="900m", node_name="n2", priority=1)]
    preemptor = make_pod(
        "high", cpu="500m", priority=100, node_selector={"disk": "nvme"}
    )
    got_node, _ = run_device_preempt(nodes, existing, preemptor)
    # pod matches NO node's selector -> no candidate anywhere
    assert got_node is None


def _make_pdb(name, match_labels, allowed=0, ns="default"):
    from kubernetes_tpu.api.types import ObjectMeta, PodDisruptionBudget

    return PodDisruptionBudget(
        metadata=ObjectMeta(name=name, namespace=ns),
        selector={"matchLabels": match_labels},
        disruptions_allowed=allowed,
    )


def test_preempt_pdb_criterion_prefers_non_violating_node():
    # both nodes preemptable; n1's victim is PDB-protected -> pick n2 even
    # though n2's victim has higher priority (criterion 1 precedes 2)
    nodes = [make_node("n1", cpu="1", mem="4Gi"), make_node("n2", cpu="1", mem="4Gi")]
    existing = [
        make_pod("prot", cpu="900m", node_name="n1", priority=1,
                 labels={"app": "guarded"}),
        make_pod("plain", cpu="900m", node_name="n2", priority=5),
    ]
    pdbs = [_make_pdb("pdb", {"app": "guarded"}, allowed=0)]
    preemptor = make_pod("high", cpu="800m", priority=100)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor, pdbs)
    golden = CPUScheduler(nodes, existing)
    want_node, want_victims = golden.preempt(preemptor, pdbs)
    assert got_node == want_node == "n2"
    assert got_victims == want_victims == {("default", "plain")}


def test_preempt_start_time_criterion():
    # identical victims except start time: pick the node whose victim
    # started LATER (criterion 5)
    nodes = [make_node("n1", cpu="1", mem="4Gi"), make_node("n2", cpu="1", mem="4Gi")]
    old = make_pod("old", cpu="900m", node_name="n1", priority=1)
    old.status.start_time = 100.0
    young = make_pod("young", cpu="900m", node_name="n2", priority=1)
    young.status.start_time = 500.0
    preemptor = make_pod("high", cpu="800m", priority=10)
    got_node, got_victims = run_device_preempt(nodes, [old, young], preemptor)
    golden = CPUScheduler(nodes, [old, young])
    want_node, want_victims = golden.preempt(preemptor)
    assert got_node == want_node == "n2"
    assert got_victims == want_victims == {("default", "young")}


def test_preempt_host_port_conflict_resolvable():
    # the preemptor's host port clashes with a low-priority pod: port
    # conflicts are resolvable (NOT in unresolvablePredicateFailureErrors),
    # and the what-if must verify the victim frees the port
    nodes = [make_node("n1", cpu="4", mem="8Gi")]
    holder = make_pod("holder", cpu="100m", node_name="n1", priority=1,
                      ports=[{"hostPort": 8080, "protocol": "TCP"}])
    preemptor = make_pod("high", cpu="100m", priority=100, ports=[{"hostPort": 8080, "protocol": "TCP"}])
    got_node, got_victims = run_device_preempt(nodes, [holder], preemptor)
    assert got_node == "n1"
    assert got_victims == {("default", "holder")}


def test_preempt_port_held_by_higher_priority_not_chosen():
    # port holder outranks the preemptor: removing lower-priority pods does
    # not free the port, so the node is not a preemption target
    nodes = [make_node("n1", cpu="4", mem="8Gi")]
    existing = [
        make_pod("portly", cpu="100m", node_name="n1", priority=1000,
                 ports=[{"hostPort": 8080, "protocol": "TCP"}]),
        make_pod("filler", cpu="100m", node_name="n1", priority=1),
    ]
    preemptor = make_pod("high", cpu="100m", priority=100, ports=[{"hostPort": 8080, "protocol": "TCP"}])
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    assert got_node is None


@pytest.mark.parametrize("seed", range(4))
def test_preempt_randomized(seed):
    rng = np.random.default_rng(4000 + seed)
    nodes = [
        make_node(f"n{i}", cpu=str(int(rng.integers(1, 4))), mem="8Gi")
        for i in range(6)
    ]
    existing = []
    for i in range(14):
        existing.append(
            make_pod(
                f"e{i}",
                cpu=f"{int(rng.integers(1, 8)) * 100}m",
                node_name=f"n{int(rng.integers(6))}",
                priority=int(rng.integers(0, 5)) * 10,  # distinct level classes
            )
        )
    preemptor = make_pod("boss", cpu="900m", priority=1000)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    golden = CPUScheduler(nodes, existing)
    want_node, want_victims = golden.preempt(preemptor)
    if want_node is None:
        assert got_node is None
    else:
        assert got_node == want_node
        assert got_victims == want_victims


def test_preempt_shared_volume_identity_credit():
    """VERDICT r4 #4 (closes PARITY §3): two victims share one PVC-backed
    EBS volume — the what-if must credit the attachment ONCE, and only
    when EVERY holder is evicted.  The old linear subtraction credited it
    per victim, so the reprieve pass wrongly re-added one holder and the
    picked victim set freed nothing.  Device must match cpuref."""
    from kubernetes_tpu.api.storage import (
        PersistentVolume, PersistentVolumeClaim,
    )
    from kubernetes_tpu.api.resource import parse_quantity

    def pvc_pod(name, claim, **kw):
        return make_pod(
            name,
            volumes=[{"persistentVolumeClaim": {"claimName": claim}}],
            **kw,
        )

    node = make_node("n1", cpu="8", mem="16Gi")
    node.status.allocatable["attachable-volumes-aws-ebs"] = parse_quantity("2")
    nodes = [node]
    pvs = [
        PersistentVolume.from_dict({
            "metadata": {"name": f"ebs{i}"},
            "spec": {"awsElasticBlockStore": {"volumeID": f"v{i}"}},
        })
        for i in (1, 2, 3)
    ]
    pvcs = [
        PersistentVolumeClaim.from_dict({
            "metadata": {"name": f"c{i}", "namespace": "default"},
            "spec": {"volumeName": f"ebs{i}"},
        })
        for i in (1, 2, 3)
    ]
    existing = [
        # BOTH low-priority victims hold the SAME volume v1 (one
        # attachment); a higher-priority pod holds v2 -> node at its
        # 2-attachment cap
        pvc_pod("shared-a", "c1", cpu="100m", node_name="n1", priority=1),
        pvc_pod("shared-b", "c1", cpu="100m", node_name="n1", priority=2),
        pvc_pod("keeper", "c2", cpu="100m", node_name="n1", priority=1000),
    ]
    # the preemptor needs a NEW attachment (v3): exactly one must free up,
    # which takes evicting BOTH holders of v1
    preemptor = pvc_pod("boss", "c3", cpu="100m", priority=2000)
    got_node, got_victims = run_device_preempt(
        nodes, existing, preemptor, pvs=pvs, pvcs=pvcs)
    golden = CPUScheduler(nodes, existing, pvs=pvs, pvcs=pvcs)
    want_node, want_victims = golden.preempt(preemptor)
    assert want_node == "n1"
    assert want_victims == {("default", "shared-a"), ("default", "shared-b")}
    assert got_node == want_node
    assert got_victims == want_victims


def test_preempt_shared_volume_with_nonvictim_holder_frees_nothing():
    """A volume held by a victim AND a surviving higher-priority pod is
    never freed: the what-if must not credit it, so preemption must
    report 'helps nowhere' (device == cpuref)."""
    from kubernetes_tpu.api.storage import (
        PersistentVolume, PersistentVolumeClaim,
    )
    from kubernetes_tpu.api.resource import parse_quantity

    def pvc_pod(name, claim, **kw):
        return make_pod(
            name,
            volumes=[{"persistentVolumeClaim": {"claimName": claim}}],
            **kw,
        )

    node = make_node("n1", cpu="8", mem="16Gi")
    node.status.allocatable["attachable-volumes-aws-ebs"] = parse_quantity("1")
    nodes = [node]
    pvs = [
        PersistentVolume.from_dict({
            "metadata": {"name": f"ebs{i}"},
            "spec": {"awsElasticBlockStore": {"volumeID": f"v{i}"}},
        })
        for i in (1, 2)
    ]
    pvcs = [
        PersistentVolumeClaim.from_dict({
            "metadata": {"name": f"c{i}", "namespace": "default"},
            "spec": {"volumeName": f"ebs{i}"},
        })
        for i in (1, 2)
    ]
    existing = [
        pvc_pod("victim", "c1", cpu="100m", node_name="n1", priority=1),
        # keeper OUTRANKS the preemptor -> it survives, and with it v1
        pvc_pod("keeper", "c1", cpu="100m", node_name="n1", priority=5000),
    ]
    preemptor = pvc_pod("boss", "c2", cpu="100m", priority=2000)
    got_node, got_victims = run_device_preempt(
        nodes, existing, preemptor, pvs=pvs, pvcs=pvcs)
    golden = CPUScheduler(nodes, existing, pvs=pvs, pvcs=pvcs)
    want_node, want_victims = golden.preempt(preemptor)
    assert want_node is None
    assert got_node is None
