"""Preemption what-if parity: device level-sweep vs golden per-pod reprieve."""

import numpy as np
import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.schema import FilterConfig
from kubernetes_tpu.cpuref import CPUScheduler
from kubernetes_tpu.models.preemption import (
    preempt_one,
    preemption_candidates,
    sorted_victim_slots,
)
from kubernetes_tpu.ops import filter_batch

from fixtures import TEST_DIMS, make_node, make_pod


def run_device_preempt(nodes, existing, preemptor):
    enc = SnapshotEncoder(TEST_DIMS)
    for n in nodes:
        enc.add_node(n)
    for p in existing:
        enc.add_pod(p)
    cluster = enc.snapshot()
    batch = enc.encode_pods([preemptor])
    _, per_pred = filter_batch(cluster, batch, FilterConfig(), 0)
    cands = preemption_candidates(np.asarray(per_pred), np.asarray(cluster.valid))[0]
    pods_node, pods_prio, pods_req, _, pods_valid, keys = enc.pods_snapshot()
    slots = sorted_victim_slots(
        pods_prio, pods_valid, pods_node, preemptor.spec.priority
    )
    res = preempt_one(
        cluster,
        np.asarray(batch.req)[0],
        cands,
        pods_node,
        pods_prio,
        pods_req,
        slots,
    )
    node_row = int(res.node)
    row_names = {row: name for name, row in enc.node_rows.items()}
    victims = {
        keys[m] for m in np.nonzero(np.asarray(res.victim_mask))[0]
    }
    return (row_names[node_row] if node_row >= 0 else None), victims


def test_preempt_basic():
    nodes = [make_node("n1", cpu="1", mem="4Gi"), make_node("n2", cpu="1", mem="4Gi")]
    existing = [
        make_pod("low-a", cpu="600m", node_name="n1", priority=1),
        make_pod("low-b", cpu="600m", node_name="n2", priority=2),
    ]
    preemptor = make_pod("high", cpu="800m", priority=100)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    golden = CPUScheduler(nodes, existing)
    want_node, want_victims = golden.preempt(preemptor)
    assert got_node == want_node
    assert got_victims == want_victims
    assert got_node == "n1"  # victim priority 1 < 2


def test_preempt_reprieve_keeps_high_priority():
    # node has two victims; evicting only the lower one suffices
    nodes = [make_node("n1", cpu="2", mem="4Gi")]
    existing = [
        make_pod("keep", cpu="500m", node_name="n1", priority=50),
        make_pod("evict", cpu="1", node_name="n1", priority=1),
    ]
    preemptor = make_pod("high", cpu="1400m", priority=100)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    golden = CPUScheduler(nodes, existing)
    want_node, want_victims = golden.preempt(preemptor)
    assert got_node == want_node == "n1"
    assert got_victims == want_victims == {("default", "evict")}


def test_preempt_impossible():
    # higher-priority occupants: nothing to evict
    nodes = [make_node("n1", cpu="1", mem="4Gi")]
    existing = [make_pod("top", cpu="900m", node_name="n1", priority=1000)]
    preemptor = make_pod("mid", cpu="500m", priority=100)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    golden = CPUScheduler(nodes, existing)
    want_node, _ = golden.preempt(preemptor)
    assert got_node is None and want_node is None
    assert got_victims == set()


def test_preempt_unresolvable_node_skipped():
    # n1 requires a selector the pod lacks: preemption can't help there
    nodes = [
        make_node("n1", cpu="4", mem="8Gi", labels={"disk": "ssd"}),
        make_node("n2", cpu="1", mem="4Gi"),
    ]
    existing = [make_pod("low", cpu="900m", node_name="n2", priority=1)]
    preemptor = make_pod(
        "high", cpu="500m", priority=100, node_selector={"disk": "nvme"}
    )
    got_node, _ = run_device_preempt(nodes, existing, preemptor)
    # pod matches NO node's selector -> no candidate anywhere
    assert got_node is None


@pytest.mark.parametrize("seed", range(4))
def test_preempt_randomized(seed):
    rng = np.random.default_rng(4000 + seed)
    nodes = [
        make_node(f"n{i}", cpu=str(int(rng.integers(1, 4))), mem="8Gi")
        for i in range(6)
    ]
    existing = []
    for i in range(14):
        existing.append(
            make_pod(
                f"e{i}",
                cpu=f"{int(rng.integers(1, 8)) * 100}m",
                node_name=f"n{int(rng.integers(6))}",
                priority=int(rng.integers(0, 5)) * 10,  # distinct level classes
            )
        )
    preemptor = make_pod("boss", cpu="900m", priority=1000)
    got_node, got_victims = run_device_preempt(nodes, existing, preemptor)
    golden = CPUScheduler(nodes, existing)
    want_node, want_victims = golden.preempt(preemptor)
    if want_node is None:
        assert got_node is None
    else:
        assert got_node == want_node
        assert got_victims == want_victims
