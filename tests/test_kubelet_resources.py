"""Kubelet resource management (runtime/kubelet_resources.py): the
cgroup/QoS hierarchy as data, the volume mount state machine, and the
observed-usage stats provider feeding metrics.k8s.io.

Reference: pkg/kubelet/cm/cgroup_manager_linux.go +
qos_container_manager_linux.go, pkg/kubelet/volumemanager,
pkg/kubelet/stats."""

import dataclasses
import json
import urllib.request

from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.kubelet_resources import (
    MIN_SHARES,
    MOUNTED,
    WAIT_FOR_ATTACH,
    CgroupManager,
    StatsProvider,
    VolumeManager,
    milli_cpu_to_shares,
)

from fixtures import make_node, make_pod


def test_cgroup_hierarchy_and_share_math():
    cm = CgroupManager()
    # MilliCPUToShares: 1000m -> 1024 shares, floor MinShares
    assert milli_cpu_to_shares(1000) == 1024
    assert milli_cpu_to_shares(250) == 256
    assert milli_cpu_to_shares(0) == MIN_SHARES

    guaranteed = make_pod("ga", cpu="500m", mem="64Mi",
                          limits={"cpu": "500m", "memory": "64Mi"})
    burstable = make_pod("bu", cpu="250m", mem="64Mi")
    besteffort = make_pod("be")
    cg_g = cm.create_pod_cgroup(guaranteed)
    cg_b = cm.create_pod_cgroup(burstable)
    cg_e = cm.create_pod_cgroup(besteffort)
    # placement: Guaranteed under kubepods, others under their qos group
    assert cg_g.name.startswith("kubepods/pod")
    assert cg_b.name.startswith("kubepods/burstable/pod")
    assert cg_e.name.startswith("kubepods/besteffort/pod")
    # per-pod resources: shares from requests, quota+memory from limits
    assert cg_g.cpu_shares == 512 and cg_g.cpu_quota == 50000
    assert cg_g.memory_limit == 64 * 1024 * 1024
    assert cg_b.cpu_shares == 256 and cg_b.cpu_quota is None
    assert cg_b.memory_limit is None        # no limit -> unlimited
    assert cg_e.cpu_shares == MIN_SHARES
    # qos-level: burstable shares track their pods; besteffort pinned
    assert cm.root.children["burstable"].cpu_shares == 256
    assert cm.root.children["besteffort"].cpu_shares == MIN_SHARES
    # removal collapses the burstable aggregate back to the floor
    cm.remove_pod_cgroup(burstable)
    assert cm.root.children["burstable"].cpu_shares == MIN_SHARES
    assert cm.get(cg_b.name) is None
    assert cm.get(cg_g.name) is not None


def test_volume_manager_waits_for_attach_then_mounts():
    from kubernetes_tpu.api.storage import (
        PersistentVolume,
        PersistentVolumeClaim,
    )

    cluster = LocalCluster()
    cluster.add_node(make_node("n1", cpu="4", mem="8Gi"))
    cluster.create("persistentvolumes", PersistentVolume.from_dict({
        "metadata": {"name": "disk1"},
        "spec": {"capacity": {"storage": "10Gi"},
                 "accessModes": ["ReadWriteOnce"],
                 "gcePersistentDisk": {"pdName": "disk1"}},
    }))
    pvc = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "c1", "namespace": "default"},
        "spec": {"volumeName": "disk1",
                 "accessModes": ["ReadWriteOnce"]},
    })
    pvc.phase = "Bound"
    cluster.create("persistentvolumeclaims", pvc)
    pod = make_pod("p1", cpu="100m", mem="64Mi")
    pod = dataclasses.replace(pod, spec=dataclasses.replace(
        pod.spec, node_name="n1",
        volumes=({"persistentVolumeClaim": {"claimName": "c1"}},
                 {"name": "scratch", "emptyDir": {}})))
    cluster.add_pod(pod)

    vm = VolumeManager(cluster, "n1")
    state = vm.sync()
    key = ("default", "p1")
    # emptyDir mounts immediately; the PV waits for the attach
    assert state[(key, "scratch")] == MOUNTED
    assert state[(key, "vol-0")] == WAIT_FOR_ATTACH
    assert not vm.all_mounted(pod)
    # the attach-detach controller surfaces the attachment -> mount
    node, rv = cluster.get_with_rv("nodes", "", "n1")
    cluster.update("nodes", dataclasses.replace(
        node, status=dataclasses.replace(
            node.status, volumes_attached=("disk1",))), expect_rv=rv)
    state = vm.sync()
    assert state[(key, "vol-0")] == MOUNTED
    assert vm.all_mounted(pod)
    # pod leaves -> unmounted (state dropped)
    cluster.delete("pods", "default", "p1")
    assert vm.sync() == {}


def test_stats_provider_publishes_observed_usage_to_metrics_api():
    """VERDICT r2 item 10 'done' check: the metrics endpoints serve
    measured (non-declared) values once a kubelet publishes stats."""
    from kubernetes_tpu.apiserver import APIServer

    cluster = LocalCluster()
    cluster.add_node(make_node("n1", cpu="4", mem="8Gi"))
    pod = make_pod("p1", cpu="200m", mem="128Mi", node_name="n1")
    pod = dataclasses.replace(
        pod, status=dataclasses.replace(pod.status, phase="Running"))
    cluster.add_pod(pod)
    stats = StatsProvider(cluster, "n1",
                          usage_fn=lambda p: (137.0, 99 * 1024 * 1024))
    assert stats.publish() == 1
    cpu, mem = stats.node_summary()
    assert cpu == 137.0 and mem == 99 * 1024 * 1024

    srv = APIServer(cluster=cluster).start()
    try:
        u = srv.url
        with urllib.request.urlopen(
            f"{u}/apis/metrics.k8s.io/v1beta1/namespaces/default/pods",
            timeout=5,
        ) as resp:
            out = json.loads(resp.read())
        item = out["items"][0]
        # 137m measured, NOT the declared 200m request
        assert item["usage"]["cpu"] == "137m"
        assert item["usage"]["memory"] == str(99 * 1024 * 1024)
        with urllib.request.urlopen(
            f"{u}/apis/metrics.k8s.io/v1beta1/nodes/n1", timeout=5,
        ) as resp:
            node_out = json.loads(resp.read())
        assert node_out["usage"]["cpu"] == "137m"
    finally:
        srv.stop()


def test_kubelet_maintains_cgroups_through_lifecycle():
    from kubernetes_tpu.runtime.kubelet import Kubelet

    cluster = LocalCluster()
    kl = Kubelet(cluster, make_node("n1", cpu="8", mem="16Gi"))
    pod = make_pod("p1", cpu="500m", mem="64Mi", node_name="n1")
    cluster.add_pod(pod)
    name = kl.cgroups.pod_cgroup_name(pod)
    assert kl.cgroups.get(name) is not None         # created on sync
    assert kl.cgroups.get(name).cpu_shares == 512
    cluster.delete("pods", "default", "p1")
    assert kl.cgroups.get(name) is None             # removed on teardown
