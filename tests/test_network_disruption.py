"""Service networking slice (endpoints controller + kube-proxy analog) and
the disruption controller (ref pkg/controller/endpoint, pkg/proxy,
pkg/controller/disruption)."""

from kubernetes_tpu.api.types import PodDisruptionBudget
from kubernetes_tpu.runtime.cluster import LocalCluster, make_cluster_binder, wire_scheduler
from kubernetes_tpu.runtime.controllers import DisruptionController
from kubernetes_tpu.runtime.kubemark import HollowFleet
from kubernetes_tpu.runtime.network import EndpointsController, ServiceProxy
from kubernetes_tpu.runtime.queue import PriorityQueue
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

from fixtures import make_node, make_pod


def _drain(ctrl, n=50):
    while ctrl.process_one(timeout=0.05) and n:
        n -= 1


def _world(n_nodes=3):
    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    fleet = HollowFleet(cluster, [make_node(f"n{i}", cpu="4") for i in range(n_nodes)])
    return cluster, sched, fleet


def test_endpoints_track_running_service_pods():
    cluster, sched, fleet = _world()
    ep_ctrl = EndpointsController(cluster)
    cluster.add_service("default", "web", {"app": "web"})
    for i in range(3):
        cluster.add_pod(make_pod(f"w{i}", cpu="100m", labels={"app": "web"}))
    cluster.add_pod(make_pod("other", cpu="100m", labels={"app": "db"}))
    sched.run_once(timeout=0.5)
    _drain(ep_ctrl)
    ep = cluster.get("endpoints", "default", "web")
    assert ep and len(ep["addresses"]) == 3
    assert {a["pod"] for a in ep["addresses"]} == {"w0", "w1", "w2"}

    # pod deletion shrinks the endpoints
    cluster.delete("pods", "default", "w1")
    _drain(ep_ctrl)
    ep = cluster.get("endpoints", "default", "web")
    assert {a["pod"] for a in ep["addresses"]} == {"w0", "w2"}


def test_proxy_round_robin_and_blackhole():
    cluster, sched, fleet = _world()
    ep_ctrl = EndpointsController(cluster)
    proxy = ServiceProxy(cluster)
    cluster.add_service("default", "web", {"app": "web"})
    for i in range(2):
        cluster.add_pod(make_pod(f"w{i}", cpu="100m", labels={"app": "web"}))
    sched.run_once(timeout=0.5)
    _drain(ep_ctrl)
    assert proxy.sync_if_dirty()
    picks = [proxy.route("default", "web")["pod"] for _ in range(4)]
    assert picks == ["w0", "w1", "w0", "w1"]  # rr over sorted backends
    # unknown / endpoint-less service blackholes
    assert proxy.route("default", "nope") is None
    v = proxy.rules_version
    cluster.add_service("default", "empty", {"app": "nothing"})
    _drain(ep_ctrl)
    proxy.sync_if_dirty()
    assert proxy.rules_version > v
    assert proxy.route("default", "empty") is None


def test_disruption_controller_maintains_allowed():
    cluster, sched, fleet = _world()
    ctrl = DisruptionController(cluster)
    pdb = PodDisruptionBudget.from_dict({
        "metadata": {"name": "web-pdb", "namespace": "default"},
        "spec": {"selector": {"matchLabels": {"app": "web"}},
                 "minAvailable": 2},
    })
    cluster.create("poddisruptionbudgets", pdb)
    for i in range(3):
        cluster.add_pod(make_pod(f"w{i}", cpu="100m", labels={"app": "web"}))
    sched.run_once(timeout=0.5)
    _drain(ctrl)
    got = cluster.get("poddisruptionbudgets", "default", "web-pdb")
    assert got.disruptions_allowed == 1  # 3 healthy - 2 minAvailable

    # percentage form: 50% of 3 -> ceil 2 -> allowed 1
    pdb2 = PodDisruptionBudget.from_dict({
        "metadata": {"name": "pct", "namespace": "default"},
        "spec": {"selector": {"matchLabels": {"app": "web"}},
                 "minAvailable": "50%"},
    })
    cluster.create("poddisruptionbudgets", pdb2)
    _drain(ctrl)
    assert cluster.get("poddisruptionbudgets", "default", "pct").disruptions_allowed == 1

    # losing a pod drops allowed to 0
    cluster.delete("pods", "default", "w0")
    _drain(ctrl)
    got = cluster.get("poddisruptionbudgets", "default", "web-pdb")
    assert got.disruptions_allowed == 0


def test_pdb_blocks_preemption_through_store():
    """End to end: the controller-maintained budget feeds PDB-aware victim
    ranking (scheduler.pdb_lister wired by wire_scheduler)."""
    cluster = LocalCluster()
    sched = Scheduler(
        cache=SchedulerCache(), queue=PriorityQueue(),
        binder=make_cluster_binder(cluster), config=SchedulerConfig(),
    )
    wire_scheduler(cluster, sched)
    assert sched.pdb_lister() == []
    pdb = PodDisruptionBudget.from_dict({
        "metadata": {"name": "p", "namespace": "default"},
        "spec": {"selector": {"matchLabels": {"app": "x"}}, "minAvailable": 1},
    })
    cluster.create("poddisruptionbudgets", pdb)
    assert len(sched.pdb_lister()) == 1


def test_ipvs_proxy_applies_only_deltas():
    """ipvs/proxier.go syncProxyRules: programmed state is DIFFED, not
    rebuilt — one endpoint change costs O(1) kernel ops regardless of
    how many other services exist (the iptables mode rewrites the
    world)."""
    from kubernetes_tpu.runtime.network import IPVSProxy

    cluster = LocalCluster()
    for i in range(50):
        cluster.add_service("default", f"svc-{i}", {"app": f"a{i}"})
        cluster.create("endpoints", {
            "namespace": "default", "name": f"svc-{i}",
            "addresses": [{"ip": f"10.0.{i}.1", "pod": f"p{i}-a"}],
        })
    proxy = IPVSProxy(cluster)
    # initial programming: one virtual + one real per service
    assert proxy.last_ops == 100
    assert proxy.route("default", "svc-3")["ip"] == "10.0.3.1"
    # ONE endpoint added to ONE service -> exactly one op
    ep, rv = cluster.get_with_rv("endpoints", "default", "svc-7")
    cluster.update("endpoints", {
        "namespace": "default", "name": "svc-7",
        "addresses": ep["addresses"] + [{"ip": "10.0.7.2", "pod": "p7-b"}],
    }, expect_rv=rv)
    assert proxy.sync_if_dirty()
    assert proxy.last_ops == 1
    assert proxy.ops[-1] == ("add-real", ("default", "svc-7"), "10.0.7.2")
    # round-robin over both backends
    got = {proxy.route("default", "svc-7")["ip"] for _ in range(2)}
    assert got == {"10.0.7.1", "10.0.7.2"}
    # removing the service tears down its virtual server only
    cluster.delete("endpoints", "default", "svc-9")
    cluster.delete("services", "default", "svc-9")
    proxy.sync_rules()
    assert proxy.last_ops == 2      # del-real + del-virtual
    assert proxy.route("default", "svc-9") is None
    # no-change sync applies nothing
    proxy.sync_rules()
    assert proxy.last_ops == 0
