"""Live multi-chip sharded control plane (ISSUE 9).

The real `Scheduler` — not the dry-run harness of tests/test_mesh.py —
running with config.shard_devices/mesh_shape: the snapshot's node axis
shards across the 8-virtual-device CPU mesh (conftest provisions
XLA_FLAGS=--xla_force_host_platform_device_count=8), every engine launch
and the incremental dirty-row upload run sharded, and placements must be
BIT-IDENTICAL to the single-chip path across chained batches, both
engines, through the express/bulk lanes, and across the full resilience
stack (breaker trip -> CPU degrade -> half-open restore).
"""

import dataclasses
import time

import jax
import numpy as np
import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec import transfer
from kubernetes_tpu.codec.faults import (
    FAULT_PERSISTENT,
    FaultInjector,
    install_injector,
)
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.queue import PriorityQueue
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig

from fixtures import TEST_DIMS, make_node, make_pod

pytestmark = pytest.mark.sharded

N_DEV = 8


# --------------------------------------------------------------- helpers


def _world(cache, n_nodes=16):
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"n{i}", cpu="8", mem="16Gi",
            labels={"disk": "ssd" if i % 2 else "hdd",
                    "tier": "a" if i % 3 else "b"},
        ))


def _sched(shard=0, mesh_shape=None, n_nodes=16, **cfg_kw):
    cache = SchedulerCache(SnapshotEncoder(TEST_DIMS))
    _world(cache, n_nodes)
    kw = dict(
        batch_size=8, batch_window_s=0.0, disable_preemption=True,
        batched_commit=True, pipeline_commit=True,
        device_backoff_base_s=0.001, device_backoff_max_s=0.005,
        breaker_open_s=0.02,
        shard_devices=shard, mesh_shape=mesh_shape,
    )
    kw.update(cfg_kw)
    return Scheduler(
        cache=cache, queue=PriorityQueue(), config=SchedulerConfig(**kw)
    )


def _pods(n, prefix="p"):
    out = []
    for i in range(n):
        out.append(make_pod(
            f"{prefix}{i}", cpu="200m", mem="256Mi",
            labels={"app": f"d{i % 3}"},
            node_selector={"disk": "ssd"} if i % 4 == 0 else None,
            priority=10 if i % 5 == 0 else 0,
        ))
    return out


def _drain(s):
    while s.queue.has_schedulable() or s.pipeline_pending:
        s.run_once(timeout=0.0)
    s.flush_pipeline()


def _placements(s):
    return [(r.pod.name, r.node) for r in s.results]


def _assert_resident_sharded(s, n_shards=N_DEV):
    res = s._dev_snapshot.resident(("allocatable", "requested", "valid"))
    assert res is not None, "no resident device snapshot after live cycles"
    for buf in res:
        assert len(buf.addressable_shards) == n_shards, buf.sharding
    # genuinely distributed, not replicated: distinct shard index ranges
    idx = {str(sh.index) for sh in res[0].addressable_shards}
    assert len(idx) == n_shards


# ------------------------------------------------- placement bit-identity


def test_sharding_off_by_default():
    s = _sched()
    assert SchedulerConfig().shard_devices == 0
    assert s.mesh is None
    assert s._dev_snapshot.mesh is None


@pytest.mark.parametrize("engine", ["speculative", "sequential"])
def test_live_chained_batches_sharded_match_single_chip(engine):
    """schedule_cycle through the real Scheduler, sharded over 8 devices,
    across CHAINED batches (committed state feeds the next snapshot):
    placements bit-identical to the single-chip path, both engines."""
    single, sharded = _sched(0, engine=engine), _sched(N_DEV, engine=engine)
    assert sharded.mesh is not None and sharded.mesh.size == N_DEV
    for s in (single, sharded):
        for p in _pods(24):
            s.queue.add(p)
        _drain(s)
    assert _placements(single) == _placements(sharded)
    assert any(r.node is not None for r in sharded.results)
    _assert_resident_sharded(sharded)


def test_two_level_dcn_ici_mesh_matches_single_chip():
    single, sharded = _sched(0), _sched(0, mesh_shape="2x4")
    assert sharded.mesh is not None
    assert tuple(sharded.mesh.axis_names) == ("dcn", "ici")
    for s in (single, sharded):
        for p in _pods(16):
            s.queue.add(p)
        _drain(s)
    assert _placements(single) == _placements(sharded)
    _assert_resident_sharded(sharded)


def test_express_bulk_interleaved_sharded_identity():
    """Interleaved express/bulk lanes on the mesh: the same pop order
    through the sharded scheduler places exactly as single-chip, and the
    express cycles really run at the express width on sharded state."""
    kw = dict(express_lane=True, express_batch_size=4,
              express_priority_threshold=1000)
    single, sharded = _sched(0, **kw), _sched(N_DEV, **kw)
    for s in (single, sharded):
        for i, p in enumerate(_pods(18, prefix="b")):
            s.queue.add(p)
        for i in range(5):
            p = make_pod(f"e{i}", cpu="100m", mem="128Mi", priority=2000)
            s.queue.add(p)
        _drain(s)
    assert _placements(single) == _placements(sharded)
    express = [r for r in sharded.results if r.pod.name.startswith("e")]
    assert len(express) == 5 and all(r.node is not None for r in express)
    _assert_resident_sharded(sharded)


# ---------------------------------------------- dirty-row shard scatter


def test_dirty_row_scatter_routes_to_owning_shard(monkeypatch):
    """The incremental upload stays O(dirty) on the mesh: a changed
    row-indexed field goes through the SHARDED scatter (not a whole-tensor
    re-upload), and afterwards every shard's block matches the host
    snapshot's rows it owns."""
    sched = _sched(N_DEV)
    cache, enc = sched.cache, sched.cache.encoder
    dsc = sched._dev_snapshot
    cluster, _ = cache.snapshot()
    enc.take_dirty_rows()  # drain the ingest-time dirty stream
    dsc.update(cluster)    # full upload: resident baseline

    scattered = []
    orig = transfer._scatter_rows_sharded

    def spy(dev, rows, vals, sharding):
        scattered.append((rows.copy(), sharding))
        return orig(dev, rows, vals, sharding)

    monkeypatch.setattr(transfer, "_scatter_rows_sharded", spy)

    # commit two pods on rows owned by DIFFERENT shards (rows 1 and 9 of
    # the 16-row axis: shards 0 and 4 on the 8-device mesh)
    cache.assume_pods([
        make_pod("d0", cpu="1", mem="1Gi", node_name="n1"),
        make_pod("d1", cpu="2", mem="2Gi", node_name="n9"),
    ])
    cluster2, _ = cache.snapshot()
    rows = enc.take_dirty_rows()
    assert len(rows) > 0
    dev2 = dsc.update(cluster2, dirty_rows=rows)

    assert scattered, "changed row fields must scatter, not re-upload"
    for rows_p, sharding in scattered:
        assert set(np.asarray(rows_p)) <= set(np.asarray(rows))
        assert not sharding.is_fully_replicated
    # the scatter path, not the whole-tensor path: the host record for
    # requested is the new snapshot array (committed by the scatter arm)
    assert dsc._host["requested"] is np.asarray(cluster2.requested)
    # per-shard content: each device's block equals the host rows it owns
    for name in ("requested", "nonzero_req", "allocatable"):
        host = np.asarray(getattr(cluster2, name))
        dev = getattr(dev2, name)
        np.testing.assert_array_equal(np.asarray(dev), host)
        assert len(dev.addressable_shards) == N_DEV
        for sh in dev.addressable_shards:
            np.testing.assert_array_equal(
                np.asarray(sh.data), host[sh.index[0]]
            )


def test_sharded_cache_rejects_indivisible_axis():
    from kubernetes_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(N_DEV)
    dsc = transfer.DeviceSnapshotCache(mesh=mesh)

    @dataclasses.dataclass
    class Tiny:
        allocatable: object

    with pytest.raises(ValueError, match="does not divide"):
        dsc.update(Tiny(allocatable=np.zeros((12, 4), np.float32)))


# --------------------------------------------------- resilience on mesh


@pytest.fixture
def injector():
    inj = FaultInjector(seed=11)
    remove = install_injector(inj)
    yield inj
    remove()


def test_breaker_trip_degrade_restore_on_mesh(injector):
    """The full resilience arc on the sharded engine: a persistent fault
    trips the breaker mid-cycle, the batch completes bit-identically via
    the CPU adapter, and after the cool-down the half-open canary
    restores the SHARDED fast path — with placements matching a healthy
    single-chip reference throughout."""
    ref = _sched(0)
    s = _sched(N_DEV)
    batch1, batch2 = _pods(8, prefix="a"), _pods(8, prefix="b")

    injector.arm("dispatch", kind=FAULT_PERSISTENT, count=1)
    res1 = s.schedule_cycle(list(batch1))
    assert all(r.node is not None for r in res1)
    assert s.device_health.state == "open"

    injector.disarm()
    time.sleep(s.config.breaker_open_s * 2)
    res2 = s.schedule_cycle(list(batch2))
    assert all(r.node is not None for r in res2)
    assert s.device_health.state == "closed"
    assert ("open", "half_open") in s.device_health.transitions
    assert ("half_open", "closed") in s.device_health.transitions

    # reference: the same two batches through a healthy single-chip path
    ref1 = ref.schedule_cycle(list(_pods(8, prefix="a")))
    ref2 = ref.schedule_cycle(list(_pods(8, prefix="b")))
    assert [r.node for r in res1] == [r.node for r in ref1]
    assert [r.node for r in res2] == [r.node for r in ref2]
    # the restore cycle re-uploaded the invalidated snapshot SHARDED
    _assert_resident_sharded(s)


def test_transient_fault_retries_same_batch_on_mesh(injector):
    injector.arm("fence", kind="transient", count=1)
    s = _sched(N_DEV)
    res = s.schedule_cycle(_pods(6))
    assert all(r.node is not None for r in res)
    assert s.device_health.state == "closed"
    _assert_resident_sharded(s)


# ------------------------------------------------ ledger across meshes


def test_ledger_record_replay_across_mesh_sizes(tmp_path):
    """Cycles recorded by the SHARDED live scheduler replay bit-identically
    (a) offline through a freshly built single-chip engine (the classic
    replay gate) and (b) through a DIFFERENTLY-SIZED mesh (4 devices) with
    the record's snapshot sharded over it — the sharded==unsharded
    identity makes the ledger mesh-portable."""
    from kubernetes_tpu.parallel.mesh import make_mesh, shard_cluster
    from kubernetes_tpu.runtime.ledger import (
        DecisionLedger,
        read_ledger,
        replay,
    )

    path = str(tmp_path / "sharded.ledger")
    s = _sched(N_DEV)
    # wire a file-backed ledger explicitly (attaching post-construction
    # mirrors what Scheduler(ledger=...) does)
    led = DecisionLedger(path=path)
    led.ensure_meta(s._engine_meta())
    s.ledger = led
    for p in _pods(16):
        s.queue.add(p)
    _drain(s)
    led.flush(10.0)
    assert led.cycles_total >= 2

    # (a) offline replay in "a fresh single-chip process"
    out = replay(path)
    assert out["bit_identical"], out

    # (b) replay through a 4-device mesh (records came from an 8-device
    # one): shard each reconstructed snapshot over the smaller mesh
    mesh4 = make_mesh(4)
    replayer = _sched(4)
    _header, records = read_ledger(path)
    assert records
    for rec in records:
        rec = dict(rec)
        rec["cluster"] = shard_cluster(rec["cluster"], mesh4)
        got = replayer.replay_cycle(rec)  # raises on any mismatch
        assert got.shape[0] == rec["n_pods"]


# ------------------------------------------------- analytics + telemetry


def test_sharded_analytics_bit_exact_vs_numpy():
    from kubernetes_tpu.ops.analytics import (
        cluster_analytics_auto,
        cluster_analytics_np,
    )

    s = _sched(N_DEV)
    for p in _pods(12):
        s.queue.add(p)
    _drain(s)
    res = s._dev_snapshot.resident(("allocatable", "requested", "valid"))
    _assert_resident_sharded(s)
    a = cluster_analytics_auto(*res)
    host = s._dev_snapshot._host
    b = cluster_analytics_np(
        host["allocatable"], host["requested"], host["valid"]
    )
    for f in dataclasses.fields(a):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f.name)), np.asarray(getattr(b, f.name)),
            err_msg=f.name,
        )


def test_telemetry_hub_samples_sharded_resident_buffers():
    s = _sched(N_DEV, telemetry=True, telemetry_interval_cycles=1)
    for p in _pods(10):
        s.queue.add(p)
    _drain(s)
    summary = s.telemetry.summary()
    assert summary["analytics"] is not None
    assert summary["analytics"]["nodes"] == 16
    assert summary["analytics"]["utilization"]["cpu"]["mean"] > 0.0


# ------------------------------------------------- prewarm + mesh config


def test_prewarm_compiles_sharded_executables():
    single, sharded = _sched(0), _sched(N_DEV)
    timings = sharded.prewarm(widths=[8])
    assert set(timings) == {8} and timings[8] > 0
    for s in (single, sharded):
        for p in _pods(8):
            s.queue.add(p)
        _drain(s)
    assert _placements(single) == _placements(sharded)
    _assert_resident_sharded(sharded)


def test_build_mesh_validation():
    from kubernetes_tpu.parallel.mesh import build_mesh, mesh_total

    with pytest.raises(ValueError, match="power of two"):
        build_mesh(6)
    with pytest.raises(ValueError, match="<= 512"):
        build_mesh(1024)  # node arenas grow in 512-multiples above 2048
    with pytest.raises(ValueError, match="devices"):
        build_mesh(512)  # pow2 and under the cap, but not provisioned
    with pytest.raises(ValueError, match="total"):
        build_mesh(8, "2x2")
    with pytest.raises(ValueError, match="total"):
        build_mesh(4, "8")  # a conflicting 1D shape is an error too
    with pytest.raises(ValueError, match="not 'N' or 'OxI'"):
        build_mesh(None, "abc")
    with pytest.raises(ValueError, match="not 'N' or 'OxI'"):
        mesh_total("2xx4")
    with pytest.raises(ValueError, match="too many dimensions"):
        mesh_total("2x2x2")  # the preflight rejects what build_mesh would
    with pytest.raises(ValueError, match="non-positive"):
        mesh_total("-2x-4")  # multiplies to a plausible total (8)
    with pytest.raises(ValueError, match="non-positive"):
        build_mesh(None, "0x8")
    mesh, axis = build_mesh(None, "8")
    assert mesh.size == 8 and axis == "nodes"
    mesh2, axis2 = build_mesh(None, "2x4")
    assert mesh2.size == 8 and axis2 == ("dcn", "ici")
    assert mesh_total("2x4") == 8
    assert mesh_total(None, 8) == 8


def test_encoder_node_capacity_floor():
    # a sharded Scheduler floors the arena at mesh.size at startup so the
    # divisibility check can never fire mid-run from a small fleet; every
    # later width on the growth schedule keeps dividing over the mesh
    from kubernetes_tpu.codec.encoder import SnapshotEncoder

    enc = SnapshotEncoder()
    assert enc._cap_n < 128
    enc.ensure_node_capacity(128)
    assert enc._cap_n >= 128 and enc._cap_n % 128 == 0
    for _ in range(8):
        enc._grow_nodes()
        assert enc._cap_n % 128 == 0


def test_component_config_plumbs_shard_knobs():
    from kubernetes_tpu.config.types import KubeSchedulerConfiguration

    cc = KubeSchedulerConfiguration.from_dict(
        {"shardDevices": 8, "meshShape": "2x4"}
    )
    assert cc.shard_devices == 8 and cc.mesh_shape == "2x4"
    sc = SchedulerConfig.from_component_config(cc)
    assert sc.shard_devices == 8 and sc.mesh_shape == "2x4"
    assert KubeSchedulerConfiguration.from_dict({}).shard_devices == 0


def test_compile_cache_topology_partitions(tmp_path):
    """A cache written single-chip is never served to a sharded process
    (and vice versa): the mesh extra lands in the directory tag."""
    from kubernetes_tpu.utils import compilecache as cc

    base = str(tmp_path / "cache")
    plain = cc.resolve_cache_dir(base)
    mesh8 = cc.resolve_cache_dir(base, topology=cc.topology_tag("mesh8"))
    mesh2x4 = cc.resolve_cache_dir(base, topology=cc.topology_tag("mesh2x4"))
    assert len({plain, mesh8, mesh2x4}) == 3
    for d in (plain, mesh8, mesh2x4):
        assert d.startswith(base)
    # same topology resolves stably (warm restarts hit the same dir)
    assert mesh8 == cc.resolve_cache_dir(
        base, topology=cc.topology_tag("mesh8")
    )
