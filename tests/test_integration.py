"""Integration: the full blackboard loop on a hollow cluster.

The analog of test/integration/scheduler (in-process apiserver + real
scheduler + nodes as API objects) and the kubemark density flow: objects go
into the LocalCluster store, the watch wiring feeds the scheduler, bindings
come back through the store, and hollow nodes drive pods to Running.
"""

import threading
import time

import numpy as np
import pytest

from kubernetes_tpu.runtime import PriorityQueue, Scheduler, SchedulerCache, SchedulerConfig
from kubernetes_tpu.runtime.cluster import LocalCluster, make_cluster_binder, wire_scheduler
from kubernetes_tpu.runtime.kubemark import HollowFleet

from fixtures import make_node, make_pod


def build_world(n_nodes=6, cpu="2"):
    cluster = LocalCluster()
    sched = Scheduler(
        SchedulerCache(),
        PriorityQueue(),
        make_cluster_binder(cluster),
        SchedulerConfig(batch_size=64, batch_window_s=0.0),
    )
    fleet = HollowFleet(cluster, [make_node(f"n{i}", cpu=cpu) for i in range(n_nodes)])
    wire_scheduler(cluster, sched)
    return cluster, sched, fleet


def drain(sched, rounds=20, timeout=0.1):
    for _ in range(rounds):
        sched.run_once(timeout=timeout)


def test_density_small():
    cluster, sched, fleet = build_world(n_nodes=4, cpu="2")
    for ns in range(3):
        cluster.add_service("default", f"svc{ns}", {"app": f"a{ns}"})
    for i in range(16):
        cluster.add_pod(make_pod(f"p{i}", cpu="400m", labels={"app": f"a{i % 3}"}))
    drain(sched)
    bound = [p for p in cluster.list("pods") if p.spec.node_name]
    assert len(bound) == 16
    # capacity respected: 2 cpu / 400m = max 5 per node
    from collections import Counter

    per_node = Counter(p.spec.node_name for p in bound)
    assert all(v <= 5 for v in per_node.values())
    # hollow nodes acknowledged everything
    deadline = time.monotonic() + 5
    while fleet.total_running < 16 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fleet.total_running == 16
    running = [p for p in cluster.list("pods") if p.status.phase == "Running"]
    assert len(running) == 16


def test_unschedulable_recovers_on_node_add():
    cluster, sched, fleet = build_world(n_nodes=1, cpu="1")
    cluster.add_pod(make_pod("big", cpu="3"))
    drain(sched, rounds=3)
    assert cluster.get("pods", "default", "big").spec.node_name == ""
    # new capacity arrives -> node event moves the pod back to active
    HollowFleet(cluster, [make_node("big-node", cpu="8")])
    time.sleep(1.1)  # backoff
    drain(sched, rounds=5, timeout=0.3)
    assert cluster.get("pods", "default", "big").spec.node_name == "big-node"


def test_node_delete_releases_and_reschedules():
    cluster, sched, fleet = build_world(n_nodes=2, cpu="2")
    for i in range(4):
        cluster.add_pod(make_pod(f"p{i}", cpu="500m"))
    drain(sched, rounds=5)
    victim_node = cluster.list("pods")[0].spec.node_name
    # delete the node; its pods are deleted (nodelifecycle analog) and
    # replacements created pending
    doomed = [p for p in cluster.list("pods") if p.spec.node_name == victim_node]
    cluster.delete("nodes", "", victim_node)
    for p in doomed:
        cluster.delete("pods", p.namespace, p.name)
        cluster.add_pod(make_pod(p.name + "-retry", cpu="500m"))
    drain(sched, rounds=5)
    for p in cluster.list("pods"):
        if p.name.endswith("-retry"):
            assert p.spec.node_name not in ("", victim_node)


def test_scheduler_thread_with_live_creates():
    """Run() in a thread while pods stream in — the real deployment shape."""
    cluster, sched, fleet = build_world(n_nodes=4, cpu="4")
    t = threading.Thread(target=sched.run, daemon=True)
    t.start()
    try:
        for i in range(30):
            cluster.add_pod(make_pod(f"s{i}", cpu="100m"))
            if i % 10 == 0:
                time.sleep(0.02)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if all(p.spec.node_name for p in cluster.list("pods")):
                break
            time.sleep(0.05)
        assert all(p.spec.node_name for p in cluster.list("pods"))
    finally:
        sched.stop()
        t.join(timeout=2)
