"""Bulk columnar node ingest: add_nodes/update_nodes equivalence.

The acceptance bar for the bulk-ingest rebuild (ISSUE 2): add_nodes(batch)
produces byte-identical arena state — including interner id order, the
topology-pair vocabulary, port maps, volume columns, and dirty-row sets —
vs. the per-node add_node loop on a mixed node set (taints, extended
resources, topology labels, unschedulable, conditions, multi-name images,
prefer-avoid annotations, attachable-volume limits), through pad-dim
growth and row recycling.
"""

import dataclasses

import numpy as np

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.schema import PadDims

from fixtures import TEST_DIMS, ZONE_KEY, REGION_KEY, make_node, make_pod


def _mixed_nodes(n=12, prefix="n"):
    """A node set exercising every column family _write_node_row touches."""
    nodes = []
    for i in range(n):
        labels = {ZONE_KEY: f"zone-{i % 3}", "tier": "a" if i % 2 else "b"}
        if i % 3 == 0:
            labels[REGION_KEY] = f"region-{i % 2}"
        if i % 4 == 0:
            labels["rank"] = str(i)  # numeric label value (Gt/Lt column)
        taints = []
        if i % 3 == 1:
            taints.append({"key": "dedicated", "value": f"team-{i % 2}",
                           "effect": "NoSchedule"})
        if i % 5 == 2:
            taints.append({"key": "gpu", "value": "", "effect": "NoExecute"})
        images = []
        if i % 2 == 0:
            images.append({
                # multiple names for ONE image: every name is a lookup key
                "names": [f"registry/app:{i}", f"registry/app@sha-{i}"],
                "sizeBytes": 100_000_000 + i,
            })
        extra = {}
        if i % 4 == 1:
            extra["example.com/gpu"] = "4"  # extended resource column
        if i % 4 == 2:
            extra["attachable-volumes-aws-ebs"] = "25"
            extra["attachable-volumes-csi-dr.example.com"] = "8"
        if i % 6 == 5:
            extra[""] = "7"  # malformed empty key: must not crash either path
        ann = None
        if i % 6 == 3:
            ann = {
                "scheduler.alpha.kubernetes.io/preferAvoidPods":
                '{"preferAvoidPods": [{"podSignature": {"podController":'
                ' {"uid": "uid-%d"}}}]}' % i
            }
        nodes.append(make_node(
            f"{prefix}{i}", cpu=f"{4 + i % 3}", mem="16Gi", pods=50,
            labels=labels, taints=taints, images=images,
            unschedulable=(i % 5 == 4),
            conditions=[{"type": "Ready", "status": "True"}]
            if i % 7 else [{"type": "Ready", "status": "False"}],
            annotations=ann, allocatable_extra=extra,
        ))
    return nodes


def _arena_fields(enc):
    return {a: getattr(enc, a) for a in dir(enc) if a.startswith("a_")}


def assert_encoders_identical(e1, e2, msg=""):
    """Byte-identical observable encoder state: arenas, vocabularies,
    bookkeeping maps, dirty sets, generation."""
    # interner id ORDER, not just content
    assert e1.interner._strs == e2.interner._strs, msg + "interner order"
    # pair vocabulary order + per-key pair columns
    assert e1._pair_vocab == e2._pair_vocab, msg + "pair vocab"
    assert e1._pair_topo_key == e2._pair_topo_key, msg + "pair topo keys"
    assert e1._res_cols == e2._res_cols, msg + "resource columns"
    assert e1._vol_cols == e2._vol_cols, msg + "volume columns"
    assert e1.dims == e2.dims, msg + "dims"
    assert e1.node_rows == e2.node_rows, msg + "node rows"
    assert e1._free_rows == e2._free_rows, msg + "free rows"
    assert e1._next_row == e2._next_row, msg + "next row"
    assert e1._image_nodes == e2._image_nodes, msg + "image nodes"
    assert e1._node_ports == e2._node_ports, msg + "port maps"
    assert e1._node_disk_vols == e2._node_disk_vols, msg + "disk vol maps"
    assert e1.generation == e2.generation, msg + "generation"
    # dirty-row bookkeeping (the transfer handshake)
    assert e1._dirty_node_rows == e2._dirty_node_rows, msg + "dirty nodes"
    assert e1._snap_dirty_all == e2._snap_dirty_all, msg + "dirty-all flag"
    a1, a2 = _arena_fields(e1), _arena_fields(e2)
    assert a1.keys() == a2.keys()
    for name, arr in a1.items():
        np.testing.assert_array_equal(
            arr, a2[name], err_msg=f"{msg}arena {name}"
        )
    for kid, col in e1._node_pair_id.items():
        np.testing.assert_array_equal(
            col, e2._node_pair_id[kid], err_msg=f"{msg}pair col {kid}"
        )


def test_add_nodes_matches_sequential_add_node():
    encs = [SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)]
    nodes = _mixed_nodes()
    for n in nodes:
        encs[0].add_node(n)
    rows = encs[1].add_nodes(nodes)
    assert rows == [encs[0].node_rows[n.name] for n in nodes]
    assert_encoders_identical(encs[0], encs[1])


def test_add_nodes_matches_through_arena_growth():
    """A batch larger than the node capacity (N growth) with a node whose
    labels/taints/images exceed the pad dims (L/T/I growth)."""
    dims = PadDims(N=4, B=4, TP=16, L=4, T=2, I=2)
    encs = [SnapshotEncoder(dims), SnapshotEncoder(dims)]
    nodes = _mixed_nodes(11, prefix="g")
    # one node that forces every pad axis to grow
    many_labels = {f"k{j}": f"v{j}" for j in range(7)}
    many_labels[ZONE_KEY] = "zone-x"
    # a many-NAMES node placed before the I-bumping node: its row truncates
    # at the pre-bump width in the sequential loop (I bumps off the image
    # COUNT, not the flattened name count) and the batch must replay that
    nodes.insert(2, make_node(
        "g-trunc", cpu="4", mem="8Gi",
        images=[{"names": [f"alias-{j}" for j in range(4)],
                 "sizeBytes": 777}],
    ))
    nodes.insert(5, make_node(
        "g-wide", cpu="8", mem="32Gi", labels=many_labels,
        taints=[{"key": f"t{j}", "value": "x", "effect": "NoSchedule"}
                for j in range(4)],
        images=[{"names": [f"img-{j}:latest"], "sizeBytes": 1000 + j}
                for j in range(5)],
    ))
    for n in nodes:
        encs[0].add_node(n)
    encs[1].add_nodes(nodes)
    assert_encoders_identical(encs[0], encs[1])


def test_add_nodes_matches_with_recycled_rows():
    """Rows freed by remove_node must come back byte-identical whether the
    re-adds go through the loop or the batch (stale label/taint content on
    recycled rows must be overwritten either way)."""
    encs = [SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)]
    first = _mixed_nodes(6, prefix="old")
    for enc in encs:
        enc.add_nodes(first) if enc is encs[1] else [
            enc.add_node(n) for n in first
        ]
        enc.remove_node("old2")
        enc.remove_node("old4")
    fresh = _mixed_nodes(4, prefix="new")
    for n in fresh:
        encs[0].add_node(n)
    encs[1].add_nodes(fresh)
    assert_encoders_identical(encs[0], encs[1])


def test_add_nodes_falls_back_for_duplicates_and_updates():
    """Duplicate names in one batch, and names already resident, must take
    the per-node (update) path and still match the loop."""
    encs = [SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)]
    for enc in encs:
        enc.add_node(make_node("resident", cpu="4", mem="8Gi"))
    batch = [
        make_node("resident", cpu="8", mem="16Gi"),  # update
        make_node("dup", cpu="2", mem="4Gi", labels={ZONE_KEY: "z-a"}),
        make_node("dup", cpu="6", mem="12Gi", labels={ZONE_KEY: "z-b"}),
    ]
    for n in batch:
        encs[0].add_node(n)
    encs[1].add_nodes(batch)
    assert_encoders_identical(encs[0], encs[1])


def test_add_nodes_snapshot_and_dirty_rows_flow():
    """The bulk path must feed the incremental snapshot/transfer handshake
    exactly like the loop: same snapshot bytes, same take_dirty_rows."""
    encs = [SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)]
    seed = _mixed_nodes(4, prefix="s")
    for enc in encs:
        for n in seed:
            enc.add_node(n)
        enc.snapshot()
        enc.take_dirty_rows()
    extra = _mixed_nodes(3, prefix="x")
    for n in extra:
        encs[0].add_node(n)
    encs[1].add_nodes(extra)
    s0 = encs[0].snapshot()
    s1 = encs[1].snapshot()
    for f in dataclasses.fields(s0):
        np.testing.assert_array_equal(
            np.asarray(getattr(s0, f.name)), np.asarray(getattr(s1, f.name)),
            err_msg=f"snapshot field {f.name}",
        )
    d0, d1 = encs[0].take_dirty_rows(), encs[1].take_dirty_rows()
    if d0 is None or d1 is None:
        assert d0 is None and d1 is None
    else:
        np.testing.assert_array_equal(d0, d1)


def test_add_nodes_with_resident_pods_on_other_rows():
    """Bulk adds must not disturb pod aggregates already charged to other
    rows (the cold-resync case interleaves with a live cluster)."""
    encs = [SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)]
    for enc in encs:
        enc.add_node(make_node("host0", cpu="8", mem="16Gi",
                               labels={ZONE_KEY: "z-0"}))
        enc.add_pod(make_pod("p0", cpu="250m", mem="128Mi",
                             node_name="host0",
                             ports=[{"hostPort": 8080, "protocol": "TCP"}]))
    more = _mixed_nodes(5, prefix="m")
    for n in more:
        encs[0].add_node(n)
    encs[1].add_nodes(more)
    assert_encoders_identical(encs[0], encs[1])
    row = encs[1].node_rows["host0"]
    assert encs[1].a_requested[row, 0] == 250.0


# --------------------------------------------------------------- update_nodes


def test_update_nodes_mixed_new_changed_unchanged():
    """update_nodes must leave the same snapshot bytes as the per-node
    upsert loop on an interleaved new/changed/unchanged list (unchanged
    nodes are skipped, which elides their generation bumps — a documented
    difference, so only content is compared)."""
    base = _mixed_nodes(6, prefix="u")
    e_loop, e_bulk = SnapshotEncoder(TEST_DIMS), SnapshotEncoder(TEST_DIMS)
    for enc in (e_loop, e_bulk):
        for n in _mixed_nodes(6, prefix="u"):
            enc.add_node(n)
    changed = make_node("u3", cpu="16", mem="64Gi",
                        labels={ZONE_KEY: "zone-moved"})
    new = make_node("u-new", cpu="2", mem="4Gi",
                    labels={ZONE_KEY: "zone-1"})
    unchanged = _mixed_nodes(6, prefix="u")[1]  # content-equal rebuild of u1
    batch = [unchanged, changed, new]
    for n in batch:
        if n.name in e_loop.node_rows:
            e_loop.update_node(n)
        else:
            e_loop.add_node(n)
    rows = e_bulk.update_nodes(batch)
    assert rows == [e_loop.node_rows[n.name] for n in batch]
    s0 = e_loop.snapshot(full=True)
    s1 = e_bulk.snapshot(full=True)
    for f in dataclasses.fields(s0):
        np.testing.assert_array_equal(
            np.asarray(getattr(s0, f.name)), np.asarray(getattr(s1, f.name)),
            err_msg=f"snapshot field {f.name}",
        )
    assert base[1].name == "u1"  # the unchanged probe really was resident


def test_update_nodes_unchanged_skip_is_free():
    """Re-listing identical nodes must not dirty rows or bump generation —
    the warm re-encode fast path."""
    enc = SnapshotEncoder(TEST_DIMS)
    nodes = _mixed_nodes(8, prefix="w")
    enc.add_nodes(nodes)
    enc.snapshot()
    enc.take_dirty_rows()
    gen = enc.generation
    relisted = _mixed_nodes(8, prefix="w")  # fresh equal objects
    rows = enc.update_nodes(relisted)
    assert rows == [enc.node_rows[n.name] for n in relisted]
    assert enc.generation == gen
    dirty = enc.take_dirty_rows()
    assert dirty is not None and len(dirty) == 0
