"""Packed transfers (codec/transfer.py): pack/unpack round-trip and the
incremental DeviceSnapshotCache reuse semantics."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.transfer import (
    DeviceSnapshotCache,
    pack_tree,
    unpack_tree,
)

from fixtures import TEST_DIMS, make_node, make_pod


def test_pack_unpack_roundtrip():
    tree = {
        "f": np.arange(12, dtype=np.float32).reshape(3, 4),
        "i": np.arange(6, dtype=np.int32).reshape(2, 3),
        "b": np.array([[True, False], [False, True]]),
        "i64": np.arange(4, dtype=np.int64),
    }
    bufs, meta = pack_tree(tree)
    assert len(bufs) == 3

    @jax.jit
    def rt(bufs):
        return unpack_tree(bufs, meta)

    out = rt(bufs)
    np.testing.assert_array_equal(np.asarray(out["f"]), tree["f"])
    np.testing.assert_array_equal(np.asarray(out["i"]), tree["i"])
    np.testing.assert_array_equal(np.asarray(out["b"]), tree["b"])
    np.testing.assert_array_equal(np.asarray(out["i64"]), tree["i64"])
    assert out["b"].dtype == jnp.bool_


def test_pack_meta_is_jit_cache_stable():
    a = {"x": np.zeros((4, 4), np.float32), "y": np.ones(3, np.int32)}
    b = {"x": np.ones((4, 4), np.float32), "y": np.zeros(3, np.int32)}
    _, ma = pack_tree(a)
    _, mb = pack_tree(b)
    assert ma == mb and hash(ma) == hash(mb)


def test_device_snapshot_cache_reuses_unchanged_fields():
    enc = SnapshotEncoder(TEST_DIMS)
    for i in range(4):
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    cache = DeviceSnapshotCache()
    d1 = cache.update(enc.snapshot())
    # a pod commit moves requested/nonzero but not the label/taint tensors
    enc.add_pod(make_pod("p0", cpu="500m", mem="512Mi", node_name="n1"))
    d2 = cache.update(enc.snapshot())
    assert d2.label_keys is d1.label_keys          # resident buffer reused
    assert d2.taint_key is d1.taint_key
    assert d2.requested is not d1.requested        # changed -> re-uploaded
    row = enc.node_rows["n1"]
    assert np.asarray(d2.requested)[row, 0] == 500.0
    # device contents always match a fresh full upload
    full = enc.snapshot()
    for f in dataclasses.fields(full):
        np.testing.assert_array_equal(
            np.asarray(getattr(d2, f.name)), np.asarray(getattr(full, f.name)),
            err_msg=f.name,
        )


def test_device_snapshot_cache_handles_regrow():
    enc = SnapshotEncoder(TEST_DIMS)
    enc.add_node(make_node("n0", cpu="4", mem="8Gi"))
    cache = DeviceSnapshotCache()
    d1 = cache.update(enc.snapshot())
    n1 = d1.valid.shape[0]
    for i in range(1, 3 * n1):  # force at least one node-arena regrow
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    d2 = cache.update(enc.snapshot())
    assert d2.valid.shape[0] > n1
    assert int(np.asarray(d2.valid).sum()) == 3 * n1


def test_pack_tree_row_factoring_roundtrip():
    """Large [B, ...] leaves with repeated rows ship factored (unique rows
    + index) and unpack bit-identically; unique-rowed leaves bail out and
    ship dense; small leaves are untouched.  factor=True forces the
    accelerator path on the CPU backend."""
    rng = np.random.default_rng(7)
    base = rng.integers(0, 2, size=(20, 16384)).astype(bool)     # 20 rows
    rep_b = base[rng.integers(0, 20, size=2048)]                 # 32MB dense
    rep_f = (base.astype(np.float32) * 3.5)[rng.integers(0, 20, size=2048)]
    uniq_f = rng.random((512, 4096)).astype(np.float32)          # no repeats
    small = rng.integers(0, 100, size=(64,)).astype(np.int32)
    tree = {"rb": rep_b, "rf": rep_f, "u": uniq_f, "s": small}
    bufs, meta = pack_tree(tree, factor=True)
    # the wire payload collapsed: repeated leaves cost ~U rows, not B
    assert sum(b.nbytes for b in bufs) < rep_b.nbytes
    out = jax.jit(lambda b: unpack_tree(b, meta))(bufs)
    for k, v in tree.items():
        np.testing.assert_array_equal(np.asarray(out[k]), v, err_msg=k)
    # meta is stable across batches of the same workload shape/content mix
    rep_b2 = base[rng.integers(0, 20, size=2048)]
    rep_f2 = (base.astype(np.float32) * 3.5)[rng.integers(0, 20, size=2048)]
    _, meta2 = pack_tree(
        {"rb": rep_b2, "rf": rep_f2, "u": uniq_f, "s": small}, factor=True
    )
    assert meta2 == meta
    # factor=False (the CPU default) keeps the legacy dense packing
    bufs_d, meta_d = pack_tree(tree, factor=False)
    out_d = jax.jit(lambda b: unpack_tree(b, meta_d))(bufs_d)
    for k, v in tree.items():
        np.testing.assert_array_equal(np.asarray(out_d[k]), v, err_msg=k)


def test_pack_tree_factoring_randomized_property():
    """Property soak: for random mixes of repeated/unique/odd-shaped
    leaves, factor=True and factor=False unpack to identical trees."""
    for seed in range(6):
        rng = np.random.default_rng(100 + seed)
        tree = {}
        for i in range(rng.integers(2, 6)):
            kind = rng.integers(0, 3)
            B = int(rng.choice([64, 257, 1024]))
            w = int(rng.choice([512, 2048, 4096]))
            if kind == 0:  # group-repeated rows
                g = int(rng.integers(1, 9))
                base = rng.random((g, w)).astype(np.float32)
                tree[f"r{i}"] = base[rng.integers(0, g, size=B)]
            elif kind == 1:  # unique rows
                tree[f"u{i}"] = rng.integers(
                    0, 2, size=(B, w)).astype(bool)
            else:  # small leaf
                tree[f"s{i}"] = rng.integers(
                    0, 50, size=(int(rng.integers(1, 64)),)
                ).astype(np.int32)
        bufs_f, meta_f = pack_tree(tree, factor=True)
        bufs_d, meta_d = pack_tree(tree, factor=False)
        out_f = jax.jit(lambda b: unpack_tree(b, meta_f))(bufs_f)
        out_d = jax.jit(lambda b: unpack_tree(b, meta_d))(bufs_d)
        for k, v in tree.items():
            np.testing.assert_array_equal(np.asarray(out_f[k]), v,
                                          err_msg=f"seed {seed} {k}")
            np.testing.assert_array_equal(np.asarray(out_d[k]), v,
                                          err_msg=f"seed {seed} {k}")


# ------------------------------------------------------- fetch worker


def test_fetch_worker_survives_raising_job():
    """Regression: a job that raises on the shared fetch worker used to
    kill the daemon thread, stranding every already-queued fetch (their
    AsyncFetch.result() hung forever).  Per-job exceptions must be
    contained and the worker must keep draining."""
    from kubernetes_tpu.codec.transfer import AsyncFetch, _fetch_worker

    w = _fetch_worker()
    w.submit(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    # a fetch queued AFTER the poison job still completes promptly
    f = AsyncFetch(np.arange(8, dtype=np.int32))
    deadline = 2.0
    import time as _t
    t0 = _t.monotonic()
    out = f.result()
    assert _t.monotonic() - t0 < deadline
    np.testing.assert_array_equal(out, np.arange(8, dtype=np.int32))
    assert w.thread.is_alive()


def test_async_fetch_routes_job_error_into_handle():
    """An error raised while materializing re-raises at result() — the
    owning handle, not the worker thread, owns the failure."""
    from kubernetes_tpu.codec.transfer import AsyncFetch

    class Evil:
        def __array__(self, *a, **k):
            raise RuntimeError("UNAVAILABLE: tunnel reset")

    f = AsyncFetch(Evil())
    with np.testing.assert_raises(RuntimeError):
        f.result()
    # and the worker still serves later fetches
    g = AsyncFetch(np.ones(3, np.float32))
    np.testing.assert_array_equal(g.result(), np.ones(3, np.float32))


def test_device_snapshot_cache_invalidate_forces_full_reupload():
    enc = SnapshotEncoder(TEST_DIMS)
    for i in range(4):
        enc.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    cache = DeviceSnapshotCache()
    d1 = cache.update(enc.snapshot())
    cache.invalidate()
    d2 = cache.update(enc.snapshot())
    # no resident buffer survived: every field re-uploaded (new objects)
    assert d2.label_keys is not d1.label_keys
    np.testing.assert_array_equal(
        np.asarray(d2.requested), np.asarray(d1.requested)
    )
