"""Volume + serviceaccount controllers (runtime/volumecontrollers.py).

Reference: pkg/controller/volume/persistentvolume/pv_controller.go,
attachdetach/attach_detach_controller.go,
serviceaccount/{serviceaccounts,tokens}_controller.go."""

import dataclasses
import time

from kubernetes_tpu.api.storage import (
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
)
from kubernetes_tpu.runtime.cluster import LocalCluster
from kubernetes_tpu.runtime.volumecontrollers import (
    AttachDetachController,
    PersistentVolumeController,
    ServiceAccountController,
    TokenController,
)

from fixtures import make_node, make_pod


def _drain(ctrl, n=50):
    for _ in range(n):
        if not ctrl.process_one(timeout=0.01):
            break


def _pv(name, size="10Gi", sc="", modes=("ReadWriteOnce",), **kw):
    return PersistentVolume.from_dict({
        "metadata": {"name": name},
        "spec": {"capacity": {"storage": size},
                 "accessModes": list(modes),
                 "storageClassName": sc,
                 "gcePersistentDisk": {"pdName": name}, **kw},
    })


def _pvc(name, ns="default", size="5Gi", sc="", modes=("ReadWriteOnce",)):
    return PersistentVolumeClaim.from_dict({
        "metadata": {"name": name, "namespace": ns},
        "spec": {"resources": {"requests": {"storage": size}},
                 "accessModes": list(modes),
                 "storageClassName": sc},
    })


def test_pv_controller_binds_smallest_fitting_volume():
    cluster = LocalCluster()
    ctrl = PersistentVolumeController(cluster)
    cluster.create("persistentvolumes", _pv("big", "100Gi"))
    cluster.create("persistentvolumes", _pv("small", "10Gi"))
    cluster.create("persistentvolumes", _pv("tiny", "1Gi"))
    cluster.create("persistentvolumeclaims", _pvc("c1", size="5Gi"))
    _drain(ctrl)
    pvc = cluster.get("persistentvolumeclaims", "default", "c1")
    assert pvc.volume_name == "small"       # smallest that fits, not "big"
    assert pvc.phase == "Bound"
    pv = cluster.get("persistentvolumes", "", "small")
    assert pv.phase == "Bound" and pv.claim_ref == "default/c1"
    # the others stay Available
    assert cluster.get("persistentvolumes", "", "big").phase == "Available"


def test_pv_controller_respects_class_and_access_modes():
    cluster = LocalCluster()
    ctrl = PersistentVolumeController(cluster)
    cluster.create("persistentvolumes", _pv("gold-pv", sc="gold"))
    cluster.create("persistentvolumes",
                   _pv("rox", modes=("ReadOnlyMany",)))
    cluster.create("persistentvolumeclaims", _pvc("c1"))  # class ""
    _drain(ctrl)
    # neither matches: gold-pv wrong class, rox wrong modes
    assert cluster.get(
        "persistentvolumeclaims", "default", "c1").volume_name == ""
    # a matching PV arriving later binds on its event
    cluster.create("persistentvolumes", _pv("plain"))
    _drain(ctrl)
    assert cluster.get(
        "persistentvolumeclaims", "default", "c1").volume_name == "plain"


def test_reclaim_policy_on_claim_deletion():
    cluster = LocalCluster()
    ctrl = PersistentVolumeController(cluster)
    retain = _pv("keepme")
    delete = dataclasses.replace(_pv("dropme"), reclaim_policy="Delete")
    cluster.create("persistentvolumes", retain)
    cluster.create("persistentvolumes", delete)
    cluster.create("persistentvolumeclaims", _pvc("c1"))
    cluster.create("persistentvolumeclaims", _pvc("c2"))
    _drain(ctrl)
    c1 = cluster.get("persistentvolumeclaims", "default", "c1")
    c2 = cluster.get("persistentvolumeclaims", "default", "c2")
    assert {c1.volume_name, c2.volume_name} == {"keepme", "dropme"}
    cluster.delete("persistentvolumeclaims", "default", "c1")
    cluster.delete("persistentvolumeclaims", "default", "c2")
    _drain(ctrl)
    kept = cluster.get("persistentvolumes", "", "keepme")
    assert kept is not None and kept.phase == "Released"   # Retain
    assert cluster.get("persistentvolumes", "", "dropme") is None  # Delete


def test_dynamic_provisioning_immediate_mode():
    cluster = LocalCluster()
    ctrl = PersistentVolumeController(cluster)
    cluster.create("storageclasses", StorageClass.from_dict({
        "metadata": {"name": "fast"}, "provisioner": "csi.example.com",
    }))
    cluster.create("persistentvolumeclaims", _pvc("c1", sc="fast"))
    _drain(ctrl)
    pvc = cluster.get("persistentvolumeclaims", "default", "c1")
    assert pvc.volume_name and pvc.phase == "Bound"
    pv = cluster.get("persistentvolumes", "", pvc.volume_name)
    assert pv.csi_driver == "csi.example.com"
    assert pv.reclaim_policy == "Delete"   # provisioned volumes get Delete
    # ... and the claim's deletion reclaims the provisioned PV
    cluster.delete("persistentvolumeclaims", "default", "c1")
    _drain(ctrl)
    assert cluster.get("persistentvolumes", "", pvc.volume_name) is None


def test_wffc_provisioning_waits_for_scheduler_then_binds():
    """The dynamic-provisioning e2e VERDICT asked for: a pod with an
    unbound WaitForFirstConsumer claim schedules (CheckVolumeBinding
    allows provisioner classes), then the PV controller provisions a PV
    pinned to the chosen node and binds the claim."""
    from kubernetes_tpu.cmd.base import build_wired_scheduler

    cluster = LocalCluster()
    sched = build_wired_scheduler(cluster)
    ctrl = PersistentVolumeController(cluster)
    cluster.add_node(make_node("n1", cpu="4", mem="8Gi"))
    cluster.create("storageclasses", StorageClass.from_dict({
        "metadata": {"name": "wffc"}, "provisioner": "csi.example.com",
        "volumeBindingMode": "WaitForFirstConsumer",
    }))
    cluster.create("persistentvolumeclaims", _pvc("data", sc="wffc"))
    _drain(ctrl)
    # no pod yet -> no provisioning
    assert cluster.get(
        "persistentvolumeclaims", "default", "data").volume_name == ""
    pod = make_pod("p1", cpu="100m", mem="64Mi")
    pod = dataclasses.replace(pod, spec=dataclasses.replace(
        pod.spec,
        volumes=({"persistentVolumeClaim": {"claimName": "data"}},)))
    cluster.add_pod(pod)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        sched.run_once(timeout=0.3)
        p = cluster.get("pods", "default", "p1")
        if p is not None and p.spec.node_name:
            break
    p = cluster.get("pods", "default", "p1")
    assert p.spec.node_name == "n1"        # scheduled despite unbound claim
    _drain(ctrl)
    pvc = cluster.get("persistentvolumeclaims", "default", "data")
    assert pvc.volume_name and pvc.phase == "Bound"
    pv = cluster.get("persistentvolumes", "", pvc.volume_name)
    # provisioned PV is pinned to the scheduler's node pick
    terms = pv.node_affinity.terms
    assert terms[0].match_expressions[0].values == ("n1",)


def test_attach_detach_surfaces_volumes_attached():
    cluster = LocalCluster()
    pvctrl = PersistentVolumeController(cluster)
    ad = AttachDetachController(cluster)
    cluster.add_node(make_node("n1", cpu="4", mem="8Gi"))
    cluster.create("persistentvolumes", _pv("disk1"))
    cluster.create("persistentvolumeclaims", _pvc("c1"))
    _drain(pvctrl)
    pod = make_pod("p1", cpu="100m", mem="64Mi")
    pod = dataclasses.replace(pod, spec=dataclasses.replace(
        pod.spec, node_name="n1",
        volumes=({"persistentVolumeClaim": {"claimName": "c1"}},)))
    cluster.add_pod(pod)
    _drain(ad)
    node = cluster.get("nodes", "", "n1")
    assert node.status.volumes_attached == ("disk1",)
    # pod leaves -> volume detaches
    cluster.delete("pods", "default", "p1")
    _drain(ad)
    assert cluster.get("nodes", "", "n1").status.volumes_attached == ()


def test_serviceaccount_and_token_controllers():
    cluster = LocalCluster()
    sactrl = ServiceAccountController(cluster)
    tkctrl = TokenController(cluster)
    cluster.create("namespaces", {"namespace": "", "name": "team"})
    _drain(sactrl)
    sa = cluster.get("serviceaccounts", "team", "default")
    assert sa is not None
    _drain(tkctrl)
    secret = cluster.get("secrets", "team", "default-token")
    assert secret is not None
    assert secret["type"] == "kubernetes.io/service-account-token"
    tok = secret["data"]["token"]
    # the minted token authenticates as the SA identity
    from kubernetes_tpu.apiserver.auth import TokenAuthenticator

    user = TokenAuthenticator(cluster).authenticate(tok)
    assert user.name == "system:serviceaccount:team:default"
    # deleting the SA reaps its token secret
    cluster.delete("serviceaccounts", "team", "default")
    _drain(tkctrl)
    assert cluster.get("secrets", "team", "default-token") is None


def test_pv_pvc_rest_round_trip():
    import json
    import urllib.request

    from kubernetes_tpu.apiserver import APIServer

    cluster = LocalCluster()
    srv = APIServer(cluster=cluster).start()
    try:
        u = srv.url
        body = json.dumps({
            "kind": "PersistentVolume", "apiVersion": "v1",
            "metadata": {"name": "pv1"},
            "spec": {"capacity": {"storage": "10Gi"},
                     "accessModes": ["ReadWriteOnce"],
                     "gcePersistentDisk": {"pdName": "pv1"}},
        }).encode()
        req = urllib.request.Request(f"{u}/api/v1/persistentvolumes",
                                     data=body, method="POST")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 201
        with urllib.request.urlopen(
                f"{u}/api/v1/persistentvolumes/pv1", timeout=5) as resp:
            d = json.loads(resp.read())
        from kubernetes_tpu.api.resource import parse_quantity

        assert float(parse_quantity(d["spec"]["capacity"]["storage"])) == \
            float(parse_quantity("10Gi"))
        assert d["spec"]["persistentVolumeReclaimPolicy"] == "Retain"
        body = json.dumps({
            "kind": "PersistentVolumeClaim", "apiVersion": "v1",
            "metadata": {"name": "c1", "namespace": "default"},
            "spec": {"resources": {"requests": {"storage": "5Gi"}},
                     "accessModes": ["ReadWriteOnce"]},
        }).encode()
        req = urllib.request.Request(
            f"{u}/api/v1/namespaces/default/persistentvolumeclaims",
            data=body, method="POST")
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 201
        assert cluster.get(
            "persistentvolumeclaims", "default", "c1") is not None
    finally:
        srv.stop()


def test_prebound_pvc_claims_the_pv_side():
    """A user-pre-bound PVC (spec.volumeName) must bind the PV too, or a
    second claim can steal the volume (syncUnboundClaim volumeName arm)."""
    cluster = LocalCluster()
    ctrl = PersistentVolumeController(cluster)
    cluster.create("persistentvolumes", _pv("pv1"))
    pvc_a = PersistentVolumeClaim.from_dict({
        "metadata": {"name": "a", "namespace": "default"},
        "spec": {"volumeName": "pv1", "accessModes": ["ReadWriteOnce"],
                 "resources": {"requests": {"storage": "1Gi"}}},
    })
    cluster.create("persistentvolumeclaims", pvc_a)
    _drain(ctrl)
    pv = cluster.get("persistentvolumes", "", "pv1")
    assert pv.phase == "Bound" and pv.claim_ref == "default/a"
    # a second claim can no longer match pv1
    cluster.create("persistentvolumeclaims", _pvc("b", size="1Gi"))
    _drain(ctrl)
    assert cluster.get(
        "persistentvolumeclaims", "default", "b").volume_name == ""


def test_prebound_pv_after_claim_completes_binding():
    """A statically pre-bound PV (spec.claimRef) created AFTER its claim
    must complete the binding (syncVolume enqueues the claim)."""
    cluster = LocalCluster()
    ctrl = PersistentVolumeController(cluster)
    cluster.create("persistentvolumeclaims", _pvc("x", sc="manual"))
    _drain(ctrl)
    assert cluster.get(
        "persistentvolumeclaims", "default", "x").volume_name == ""
    pv = dataclasses.replace(_pv("pvx", sc="manual"),
                             claim_ref="default/x")
    cluster.create("persistentvolumes", pv)
    _drain(ctrl)
    pvc = cluster.get("persistentvolumeclaims", "default", "x")
    assert pvc.volume_name == "pvx" and pvc.phase == "Bound"


def test_prebound_pv_whose_claim_bound_elsewhere_resets_available():
    """claimRef pointing at a claim that bound another volume: the unused
    PV resets to Available — NOT reclaimed (no data loss)."""
    cluster = LocalCluster()
    ctrl = PersistentVolumeController(cluster)
    cluster.create("persistentvolumes", _pv("pv-b"))
    cluster.create("persistentvolumeclaims", _pvc("x", size="1Gi"))
    _drain(ctrl)
    assert cluster.get(
        "persistentvolumeclaims", "default", "x").volume_name == "pv-b"
    stray = dataclasses.replace(_pv("pv-a"), claim_ref="default/x",
                                reclaim_policy="Delete")
    cluster.create("persistentvolumes", stray)
    _drain(ctrl)
    pv_a = cluster.get("persistentvolumes", "", "pv-a")
    assert pv_a is not None                 # NOT deleted despite Delete
    assert pv_a.phase == "Available" and pv_a.claim_ref == ""


def test_token_cleaner_reaps_expired_bootstrap_tokens():
    import time as _t

    from kubernetes_tpu.runtime.volumecontrollers import TokenCleaner

    cluster = LocalCluster()
    tc = TokenCleaner(cluster)
    now = _t.time()
    cluster.create("secrets", {
        "namespace": "kube-system", "name": "bootstrap-token-old",
        "type": "bootstrap.kubernetes.io/token",
        "data": {"token-id": "old", "token-secret": "x" * 16,
                 "expiration": now - 10},
    })
    cluster.create("secrets", {
        "namespace": "kube-system", "name": "bootstrap-token-live",
        "type": "bootstrap.kubernetes.io/token",
        "data": {"token-id": "live", "token-secret": "y" * 16,
                 "expiration": now + 3600},
    })
    cluster.create("secrets", {   # no expiration: never reaped
        "namespace": "kube-system", "name": "bootstrap-token-forever",
        "type": "bootstrap.kubernetes.io/token",
        "data": {"token-id": "forever", "token-secret": "z" * 16},
    })
    assert tc.tick() == 1
    assert cluster.get("secrets", "kube-system", "bootstrap-token-old") is None
    assert cluster.get("secrets", "kube-system",
                       "bootstrap-token-live") is not None
    assert cluster.get("secrets", "kube-system",
                       "bootstrap-token-forever") is not None


def test_nodeipam_assigns_unique_pod_cidrs():
    from kubernetes_tpu.runtime.volumecontrollers import NodeIpamController

    cluster = LocalCluster()
    ctrl = NodeIpamController(cluster, cluster_cidr="10.244.0.0/22",
                              node_mask=24)
    for i in range(4):
        cluster.add_node(make_node(f"n{i}", cpu="4", mem="8Gi"))
    _drain(ctrl)
    cidrs = [cluster.get("nodes", "", f"n{i}").spec.pod_cidr
             for i in range(4)]
    assert all(cidrs)
    assert len(set(cidrs)) == 4                 # unique per node
    assert cidrs[0].startswith("10.244.")
    # a node keeps its assignment across re-syncs
    ctrl.queue.add("n0")
    _drain(ctrl)
    assert cluster.get("nodes", "", "n0").spec.pod_cidr == cidrs[0]
    # freed slot is reused by the next node
    cluster.delete("nodes", "", "n2")
    cluster.add_node(make_node("n9", cpu="4", mem="8Gi"))
    _drain(ctrl)
    assert cluster.get("nodes", "", "n9").spec.pod_cidr == cidrs[2]


def test_replication_controller_reconciles():
    """The core/v1 workload kind rides the parameterized RS reconcile."""
    from kubernetes_tpu.runtime.controllers import (
        ReplicationController,
        ReplicationControllerController,
    )

    cluster = LocalCluster()
    ctrl = ReplicationControllerController(cluster)
    cluster.create("replicationcontrollers", ReplicationController(
        namespace="default", name="web-rc", replicas=3,
        selector={"app": "web"},
        template={"metadata": {"labels": {"app": "web"}},
                  "spec": {"containers": [{"name": "c"}]}},
    ))
    _drain(ctrl)
    pods = [p for p in cluster.list("pods")
            if p.labels.get("app") == "web"]
    assert len(pods) == 3
    assert all(p.metadata.owner_kind == "ReplicationController"
               for p in pods)
    # scale down through the store
    import dataclasses as _dc

    rc, rv = cluster.get_with_rv("replicationcontrollers", "default",
                                 "web-rc")
    cluster.update("replicationcontrollers", _dc.replace(rc, replicas=1),
                   expect_rv=rv)
    _drain(ctrl)
    assert len([p for p in cluster.list("pods")
                if p.labels.get("app") == "web"]) == 1
    # deleting the RC cascades its pods
    cluster.delete("replicationcontrollers", "default", "web-rc")
    _drain(ctrl)
    assert not [p for p in cluster.list("pods")
                if p.labels.get("app") == "web"]


def test_rc_namespace_teardown_and_gc_coverage():
    """Integration guards from review: RC participates in namespace
    teardown (NAMESPACED_KINDS) and the GC backstop (OWNER_KINDS)."""
    from kubernetes_tpu.apiserver.admission import NAMESPACED_KINDS
    from kubernetes_tpu.runtime.controllers import GarbageCollector

    assert "replicationcontrollers" in NAMESPACED_KINDS
    assert GarbageCollector.OWNER_KINDS.get(
        "replicationcontrollers") == "ReplicationController"
