"""API object-model tests: quantities, selectors, tolerations."""

import pytest

from kubernetes_tpu.api import parse_quantity
from kubernetes_tpu.api.labels import (
    Requirement,
    selector_from_label_selector,
    selector_from_match_labels,
)
from kubernetes_tpu.api.types import Taint, Toleration


@pytest.mark.parametrize(
    "s,milli,value",
    [
        ("100m", 100, 1),
        ("1", 1000, 1),
        ("2", 2000, 2),
        ("1500m", 1500, 2),
        ("0.5", 500, 1),
        ("2Gi", 2 * 1024**3 * 1000, 2 * 1024**3),
        ("128Mi", 128 * 1024**2 * 1000, 128 * 1024**2),
        ("1G", 10**9 * 1000, 10**9),
        ("1e3", 10**6, 1000),
        ("5k", 5000 * 1000, 5000),
        (".5", 500, 1),
    ],
)
def test_parse_quantity(s, milli, value):
    q = parse_quantity(s)
    assert q.milli == milli
    assert q.scalar == value


def test_quantity_arithmetic():
    a = parse_quantity("1500m")
    b = parse_quantity("500m")
    assert (a + b).milli == 2000
    assert (a - b).milli == 1000
    assert b < a


def test_selector_match_labels():
    sel = selector_from_match_labels({"app": "web", "tier": "fe"})
    assert sel.matches({"app": "web", "tier": "fe", "extra": "x"})
    assert not sel.matches({"app": "web"})


def test_selector_expressions():
    sel = selector_from_label_selector(
        {
            "matchExpressions": [
                {"key": "env", "operator": "In", "values": ["prod", "staging"]},
                {"key": "canary", "operator": "DoesNotExist"},
            ]
        }
    )
    assert sel.matches({"env": "prod"})
    assert not sel.matches({"env": "dev"})
    assert not sel.matches({"env": "prod", "canary": "true"})


def test_not_in_absent_key_matches():
    # labels.Requirement semantics: NotIn matches when the key is absent
    assert Requirement("x", "NotIn", ("a",)).matches({})
    assert not Requirement("x", "In", ("a",)).matches({})


def test_gt_lt():
    assert Requirement("n", "Gt", ("5",)).matches({"n": "7"})
    assert not Requirement("n", "Gt", ("5",)).matches({"n": "5"})
    assert Requirement("n", "Lt", ("5",)).matches({"n": "3"})
    assert not Requirement("n", "Gt", ("5",)).matches({"n": "abc"})


def test_toleration_matrix():
    taint = Taint(key="k", value="v", effect="NoSchedule")
    assert Toleration(key="k", operator="Equal", value="v", effect="NoSchedule").tolerates(taint)
    assert Toleration(key="k", operator="Exists", effect="NoSchedule").tolerates(taint)
    assert Toleration(key="k", operator="Exists").tolerates(taint)  # empty effect = all
    assert Toleration(operator="Exists").tolerates(taint)  # empty key = all keys
    assert not Toleration(key="k", operator="Equal", value="w").tolerates(taint)
    assert not Toleration(key="other", operator="Exists").tolerates(taint)
    assert not Toleration(key="k", operator="Exists", effect="NoExecute").tolerates(taint)
