"""Binary wire format + content negotiation (api/binary.py).

Reference: apimachinery runtime/serializer/protobuf/protobuf.go — the
k8s\\x00 envelope, negotiated via Accept/Content-Type for the high-QPS
paths; LIST/WATCH move several times fewer bytes than JSON."""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.api import binary
from kubernetes_tpu.api.serialize import node_to_dict, pod_to_dict
from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.runtime.cluster import LocalCluster

from fixtures import make_node, make_pod


# ------------------------------------------------------------------ codec


def test_round_trip_values():
    cases = [
        None, True, False, 0, 1, -1, 2 ** 40, -(2 ** 40), 3.5, -0.25,
        "", "hello", "ünïcødé",
        [], [1, "a", None, [2.5, True]],
        {}, {"a": 1, "b": {"c": [1, 2, 3]}, "": "empty-key"},
        b"\x00\xffbytes",
    ]
    for v in cases:
        assert binary.loads(binary.dumps(v)) == v


def test_magic_envelope_enforced():
    with pytest.raises(ValueError):
        binary.loads(b"{}")
    assert binary.dumps({})[:4] == binary.MAGIC


def test_round_trip_scheme_kinds():
    """Every registered kind's wire dict survives the binary codec."""
    from kubernetes_tpu.api import scheme

    node = make_node("n1", cpu="4", mem="8Gi",
                     labels={"zone": "a"},
                     taints=[{"key": "k", "value": "v",
                              "effect": "NoSchedule"}])
    pod = make_pod("p1", cpu="250m", mem="256Mi",
                   labels={"app": "web"},
                   ports=[{"hostPort": 80, "protocol": "TCP"}])
    for kind, obj in (("nodes", node), ("pods", pod)):
        wire = scheme.encode(kind, obj)
        assert binary.loads(binary.dumps(wire)) == wire
    # dict kinds (rbac, secrets) ride verbatim
    secret = {"namespace": "ns", "name": "s", "type": "Opaque",
              "data": {"k": "v"}}
    assert binary.loads(binary.dumps(secret)) == secret


def test_string_table_dedups_repeats():
    """The per-message string table is where LIST savings come from:
    repeated keys/values cost a varint, not a full string."""
    items = [{"metadata": {"name": f"pod-{i}", "namespace": "default"},
              "spec": {"containers": [{"name": "c", "image": "repo/app:v1"}]}}
             for i in range(100)]
    payload = {"kind": "PodList", "items": items}
    b = binary.dumps(payload)
    j = json.dumps(payload).encode()
    assert len(b) < len(j) * 0.5   # >2x smaller on a repetitive LIST


# ------------------------------------------------------------ negotiation


def _req(url, method="GET", payload=None, accept=None, ct=None):
    headers = {}
    data = None
    if payload is not None:
        if ct == binary.BINARY_MEDIA_TYPE:
            data = binary.dumps(payload)
        else:
            data = json.dumps(payload).encode()
        headers["Content-Type"] = ct or "application/json"
    if accept:
        headers["Accept"] = accept
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


def test_rest_negotiation_round_trip():
    cluster = LocalCluster()
    srv = APIServer(cluster=cluster).start()
    try:
        u = srv.url
        # binary POST body
        code, _ct, _body = _req(
            f"{u}/api/v1/nodes", "POST",
            payload=node_to_dict(make_node("n1", cpu="4", mem="8Gi")),
            ct=binary.BINARY_MEDIA_TYPE)
        assert code == 201
        assert cluster.get("nodes", "", "n1") is not None
        # binary GET via Accept
        code, ct, body = _req(f"{u}/api/v1/nodes/n1",
                              accept=binary.BINARY_MEDIA_TYPE)
        assert code == 200 and ct == binary.BINARY_MEDIA_TYPE
        d = binary.loads(body)
        assert d["metadata"]["name"] == "n1"
        # JSON stays the default
        code, ct, body = _req(f"{u}/api/v1/nodes/n1")
        assert ct == "application/json"
        assert json.loads(body)["metadata"]["name"] == "n1"
    finally:
        srv.stop()


def test_binary_watch_stream_and_reflector():
    from kubernetes_tpu.client import Reflector

    cluster = LocalCluster()
    srv = APIServer(cluster=cluster).start()
    try:
        cluster.add_node(make_node("n1", cpu="4", mem="8Gi"))
        refl = Reflector(srv.url, binary=True).start()
        try:
            assert refl.wait_for_sync(5)
            assert refl.mirror.get("nodes", "", "n1") is not None
            # live event over the binary stream
            cluster.add_pod(make_pod("p1", cpu="100m", mem="64Mi"))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if refl.mirror.get("pods", "default", "p1") is not None:
                    break
                time.sleep(0.02)
            assert refl.mirror.get("pods", "default", "p1") is not None
            # remote resourceVersion still round-trips over binary
            _, rv = refl.mirror.get_with_rv("pods", "default", "p1")
            _, remote_rv = cluster.get_with_rv("pods", "default", "p1")
            assert rv == remote_rv
        finally:
            refl.stop()
    finally:
        srv.stop()


def test_list_throughput_json_vs_binary_kubemark_scale():
    """The measurement VERDICT item 8 asked for: LIST bytes+time at
    hollow-fleet scale, JSON vs binary.  Asserts the byte win; prints
    both so the numbers land in CI logs."""
    cluster = LocalCluster()
    for i in range(300):
        cluster.add_node(make_node(f"n{i}", cpu="8", mem="32Gi",
                                   labels={"zone": f"z{i % 8}"}))
    for i in range(1500):
        cluster.add_pod(make_pod(
            f"p{i}", cpu="100m", mem="64Mi",
            labels={"app": f"dep-{i % 20}"}, node_name=f"n{i % 300}"))
    srv = APIServer(cluster=cluster).start()
    try:
        u = srv.url

        def fetch(accept=None):
            t0 = time.monotonic()
            code, ct, body = _req(f"{u}/api/v1/namespaces/default/pods",
                                  accept=accept)
            dt = time.monotonic() - t0
            assert code == 200
            return len(body), dt, ct

        jb, jt, _ = fetch()
        bb, bt, ct = fetch(accept=binary.BINARY_MEDIA_TYPE)
        assert ct == binary.BINARY_MEDIA_TYPE
        items = binary.loads(
            _req(f"{u}/api/v1/namespaces/default/pods",
                 accept=binary.BINARY_MEDIA_TYPE)[2])["items"]
        assert len(items) == 1500
        print(f"\nLIST 1500 pods: json={jb}B/{jt * 1e3:.1f}ms "
              f"binary={bb}B/{bt * 1e3:.1f}ms "
              f"({jb / bb:.2f}x smaller)")
        assert bb < jb * 0.6   # >=1.7x byte win at kubemark scale
    finally:
        srv.stop()
