"""Kernel sanitizer harness: checkify float/index guards over the device
kernels on randomized cluster states.

Reference analog: the Go race detector runs under every unit/integration
test (hack/make-rules/test.sh KUBE_RACE=-race, SURVEY section 5); for
XLA kernels the equivalent guardrail is jax.experimental.checkify's
float_checks (NaN/Inf surfacing through any fused op) and index_checks
(gather/scatter bounds) — nothing here asserts semantics (the
differential suites do that); this asserts no non-finite value or OOB
index can escape a kernel edit unnoticed."""

import numpy as np
import pytest
from jax.experimental import checkify

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.schema import FilterConfig
from kubernetes_tpu.models.batched import encode_batch_ports
from kubernetes_tpu.ops import filter_batch, score_batch, select_hosts_batch

from fixtures import TEST_DIMS, make_node, make_pod

ZONE = "failure-domain.beta.kubernetes.io/zone"


def _random_world(seed: int, n_nodes=24, n_existing=40, n_pending=12):
    rng = np.random.default_rng(seed)
    enc = SnapshotEncoder(TEST_DIMS)
    for i in range(n_nodes):
        enc.add_node(make_node(
            f"n{i}",
            cpu=str(int(rng.integers(1, 32))),
            mem=f"{int(rng.integers(1, 64))}Gi",
            pods=int(rng.integers(4, 110)),
            labels={ZONE: f"z{int(rng.integers(0, 4))}",
                    "disk": "ssd" if rng.random() < 0.5 else "hdd"},
            taints=[{"key": "dedicated", "value": "x",
                     "effect": "NoSchedule"}] if rng.random() < 0.1 else [],
        ))
    enc.add_spread_selector("default", {"app": "web"})
    for i in range(n_existing):
        enc.add_pod(make_pod(
            f"e{i}", cpu=f"{int(rng.integers(50, 2000))}m",
            mem=f"{int(rng.integers(32, 2048))}Mi",
            labels={"app": "web" if rng.random() < 0.5 else "db"},
            node_name=f"n{int(rng.integers(0, n_nodes))}",
        ))
    pending = [
        make_pod(
            f"p{i}", cpu=f"{int(rng.integers(50, 4000))}m",
            mem=f"{int(rng.integers(32, 4096))}Mi",
            labels={"app": "web"},
            node_selector={"disk": "ssd"} if rng.random() < 0.3 else None,
            ports=[{"hostPort": int(rng.integers(8000, 8004)),
                    "protocol": "TCP"}] if rng.random() < 0.2 else (),
        )
        for i in range(n_pending)
    ]
    batch = enc.encode_pods(pending)
    cluster = enc.snapshot()
    ports = encode_batch_ports(enc, pending)
    return enc, cluster, batch, ports


@pytest.mark.parametrize("seed", [0, 7, 1234])
def test_filter_score_select_under_checkify(seed):
    enc, cluster, batch, ports = _random_world(seed)
    cfg = FilterConfig()
    unsched = enc.interner.intern("node.kubernetes.io/unschedulable")

    def kernel(cluster, batch):
        mask, per_pred = filter_batch(cluster, batch, cfg, unsched)
        total, parts = score_batch(cluster, batch,
                                   zone_key_id=enc.getzone_key)
        hosts, feasible = select_hosts_batch(total, mask, 0)
        return mask, total, hosts, feasible

    checked = checkify.checkify(
        kernel, errors=checkify.float_checks | checkify.index_checks)
    err, (mask, total, hosts, feasible) = checked(cluster, batch)
    err.throw()   # any NaN/Inf or OOB gather inside the fused kernels
    total = np.asarray(total)
    assert np.isfinite(total).all()
    hosts = np.asarray(hosts)
    assert ((hosts >= -1) & (hosts < cluster.valid.shape[0])).all()
    # feasibility consistent with the mask
    m = np.asarray(mask)
    f = np.asarray(feasible)
    np.testing.assert_array_equal(f, m.any(axis=1))


@pytest.mark.parametrize("engine", ["sequential", "speculative"])
def test_engines_produce_finite_committed_state(engine):
    from kubernetes_tpu.models.batched import make_sequential_scheduler
    from kubernetes_tpu.models.speculative import make_speculative_scheduler

    enc, cluster, batch, ports = _random_world(99)
    maker = (make_sequential_scheduler if engine == "sequential"
             else make_speculative_scheduler)
    fn = maker(
        unsched_taint_key=enc.interner.intern(
            "node.kubernetes.io/unschedulable"),
        zone_key_id=enc.getzone_key,
    )
    hosts, new_cluster = fn(cluster, batch, ports, np.int32(0))
    req = np.asarray(new_cluster.requested)
    assert np.isfinite(req).all()
    assert (req >= 0).all()
    hosts = np.asarray(hosts)
    assert ((hosts >= -1) & (hosts < cluster.valid.shape[0])).all()
