"""Latency-tiered express lane (ISSUE 6).

Pins the tentpole's contracts:
  * tier CLASSIFICATION at queue admission: annotation opt-in/out beats
    the priority threshold, default is bulk, gang members never ride
    express;
  * queue ROUTING: express pods surface only through pop_express_batch,
    bulk pops yield to an express arrival, depth/shed/delete accounting
    spans both lanes;
  * STARVATION guards both ways: bulk drains under sustained express
    load, express pods schedule promptly under a saturating bulk
    backlog;
  * placement BIT-IDENTITY: the interleaved two-lane run places every
    pod exactly where a single-lane scheduler replaying the same pop
    order does (both engines);
  * observability: tier label on the e2e histogram + phase counters,
    tier annotation on the schedule_cycle span, tier in postmortem
    state;
  * the raw-speed satellites: Scheduler.prewarm compiles the shared
    AIMD pow2 ladder (codec.schema.aimd_pow2_widths — the same list
    bench warmup sweeps) without perturbing placements, and
    utils/compilecache.py resolves/enables the persistent cache knob.
"""

import os

import pytest

from kubernetes_tpu.codec import SnapshotEncoder
from kubernetes_tpu.codec.schema import aimd_pow2_widths
from kubernetes_tpu.runtime.cache import SchedulerCache
from kubernetes_tpu.runtime.queue import (
    LATENCY_TIER_ANNOTATION,
    TIER_BULK,
    TIER_EXPRESS,
    PriorityQueue,
    classify_tier,
)
from kubernetes_tpu.runtime.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.utils import metrics as m

from fixtures import make_node, make_pod

ZONE = "failure-domain.beta.kubernetes.io/zone"


def _cluster(n_nodes=6, cpu="16", mem="32Gi"):
    enc = SnapshotEncoder()
    enc.add_nodes([
        make_node(f"n{i}", cpu=cpu, mem=mem,
                  labels={ZONE: f"z{i % 3}", "tier": "a" if i % 2 else "b"})
        for i in range(n_nodes)
    ])
    enc.add_spread_selector("default", {"app": "web"})
    return SchedulerCache(enc)


def _sched(cache=None, queue=None, binder=None, **cfg):
    cfg.setdefault("disable_preemption", True)
    cfg.setdefault("batch_size", 32)
    cfg.setdefault("batch_window_s", 0.0)
    return Scheduler(
        cache=cache if cache is not None else _cluster(),
        queue=queue,
        binder=binder or (lambda p, n: True),
        config=SchedulerConfig(**cfg),
    )


# ------------------------------------------------------------ classification


def test_classify_priority_threshold():
    hi = make_pod("hi", cpu="1", priority=2000)
    lo = make_pod("lo", cpu="1", priority=10)
    assert classify_tier(hi, 1000) == TIER_EXPRESS
    assert classify_tier(lo, 1000) == TIER_BULK
    # boundary is inclusive
    assert classify_tier(make_pod("edge", cpu="1", priority=1000), 1000) \
        == TIER_EXPRESS
    # no threshold -> priority alone never promotes
    assert classify_tier(hi, None) == TIER_BULK


def test_classify_annotation_wins_both_directions():
    opt_in = make_pod(
        "in", cpu="1",
        annotations={LATENCY_TIER_ANNOTATION: "express"},
    )
    opt_out = make_pod(
        "out", cpu="1", priority=5000,
        annotations={LATENCY_TIER_ANNOTATION: "bulk"},
    )
    junk = make_pod(
        "junk", cpu="1",
        annotations={LATENCY_TIER_ANNOTATION: "turbo"},
    )
    assert classify_tier(opt_in, None) == TIER_EXPRESS
    # explicit bulk opt-out beats a qualifying priority
    assert classify_tier(opt_out, 1000) == TIER_BULK
    # unknown annotation value falls through to the default
    assert classify_tier(junk, None) == TIER_BULK


def test_classify_default_is_bulk():
    assert classify_tier(make_pod("p", cpu="1"), None) == TIER_BULK


def test_gang_members_never_express():
    s = _sched(express_lane=True, express_priority_threshold=100)
    gang = make_pod("g0", cpu="1", priority=500,
                    labels={Scheduler.POD_GROUP_LABEL: "grp"})
    assert s._tier_of(gang) == TIER_BULK
    plain = make_pod("p0", cpu="1", priority=500)
    assert s._tier_of(plain) == TIER_EXPRESS


# ------------------------------------------------------------- queue routing


def test_express_routes_to_express_heap():
    q = PriorityQueue(tier_of=lambda p: classify_tier(p, 1000))
    q.add(make_pod("bulk1", cpu="1"))
    q.add(make_pod("exp1", cpu="1", priority=2000))
    q.add(make_pod("exp2", cpu="1",
                   annotations={LATENCY_TIER_ANNOTATION: "express"}))
    assert len(q) == 3
    assert q.express_depth() == 2
    # bulk pop never surfaces express pods
    assert q.pop(timeout=0.0).name == "bulk1"
    assert q.pop(timeout=0.0) is None
    got = [p.name for p in q.pop_express_batch(8)]
    assert got == ["exp1", "exp2"]  # priority order within the lane
    assert len(q) == 0


def test_bulk_pop_yields_to_express_arrival():
    import threading
    import time

    q = PriorityQueue(tier_of=lambda p: classify_tier(p, 1000))

    def _arrive():
        time.sleep(0.05)
        q.add(make_pod("exp", cpu="1", priority=2000))

    threading.Thread(target=_arrive, daemon=True).start()
    t0 = time.monotonic()
    # the bulk pop must NOT sit out its 5s timeout: the express arrival
    # interrupts it (returns None) so the run loop can serve the lane
    assert q.pop(timeout=5.0, yield_to_express=True) is None
    assert time.monotonic() - t0 < 2.0
    assert q.express_depth() == 1


def test_delete_and_requeue_respect_lanes():
    q = PriorityQueue(tier_of=lambda p: classify_tier(p, 1000))
    exp = make_pod("exp", cpu="1", priority=2000)
    q.add(exp)
    q.delete(exp)
    assert q.pop_express_batch(8) == []
    # an unschedulable requeue + move_all re-classifies back to express
    q.add(exp)
    [got] = q.pop_express_batch(8)
    q.add_unschedulable(got, q.scheduling_cycle)
    q.move_all_to_active()
    import time
    deadline = time.monotonic() + 5.0
    popped = []
    while not popped and time.monotonic() < deadline:
        popped = q.pop_express_batch(8)  # backoff expiry promotes it
        time.sleep(0.05)
    assert [p.name for p in popped] == ["exp"]


# ------------------------------------------------------- scheduler interleave


def test_express_pods_schedule_with_tier_metrics():
    cache = _cluster()
    q = PriorityQueue()
    s = _sched(cache=cache, queue=q, express_lane=True,
               express_priority_threshold=1000, express_batch_size=8)
    exp_before = m.E2E_LATENCY.labels(tier=TIER_EXPRESS).total
    bulk_before = m.E2E_LATENCY.labels(tier=TIER_BULK).total
    phase_before = m.CYCLE_PHASE_SECONDS.value(
        phase="encode", tier=TIER_EXPRESS
    )
    for i in range(5):
        q.add(make_pod(f"b{i}", cpu="100m"))
    for i in range(3):
        q.add(make_pod(f"e{i}", cpu="100m", priority=2000))
    placed = s.run_once(timeout=0.2)
    assert placed == 8
    assert m.E2E_LATENCY.labels(tier=TIER_EXPRESS).total == exp_before + 3
    assert m.E2E_LATENCY.labels(tier=TIER_BULK).total == bulk_before + 5
    assert m.CYCLE_PHASE_SECONDS.value(
        phase="encode", tier=TIER_EXPRESS
    ) > phase_before
    # the express cycle's span carries the tier annotation, and the
    # postmortem state records the last-dispatched tier
    spans = s.flight_recorder.spans()
    tiers = {sp.attrs.get("tier") for sp in spans}
    assert TIER_EXPRESS in tiers and TIER_BULK in tiers
    assert s._postmortem_state()["tier"] in (TIER_EXPRESS, TIER_BULK)


def test_bulk_drains_under_sustained_express_load():
    cache = _cluster(n_nodes=8, cpu="64")
    q = PriorityQueue()
    s = _sched(cache=cache, queue=q, express_lane=True,
               express_priority_threshold=1000, express_batch_size=4,
               batch_size=8)
    for i in range(16):
        q.add(make_pod(f"b{i}", cpu="10m"))
    seq = 0
    bulk_placed = 0
    # every iteration ADDS a full express batch — sustained express
    # pressure; the interleave must still hand the bulk lane one cycle
    # per iteration
    for _ in range(12):
        for _ in range(4):
            q.add(make_pod(f"e{seq}", cpu="10m", priority=2000))
            seq += 1
        s.run_once(timeout=0.05)
        bulk_placed = sum(
            1 for r in s.results
            if r.node is not None and r.pod.name.startswith("b")
        )
        if bulk_placed == 16:
            break
    assert bulk_placed == 16, f"bulk starved: {bulk_placed}/16 placed"


def test_express_served_promptly_under_bulk_saturation():
    cache = _cluster(n_nodes=8, cpu="64")
    q = PriorityQueue()
    s = _sched(cache=cache, queue=q, express_lane=True,
               express_priority_threshold=1000, express_batch_size=8,
               batch_size=16)
    # saturating bulk backlog: many more pods than one cycle drains
    for i in range(200):
        q.add(make_pod(f"b{i}", cpu="10m"))
    s.run_once(timeout=0.05)  # bulk lane mid-drain
    q.add(make_pod("urgent", cpu="10m", priority=2000))
    # the very next iteration must place the express pod, with ~all of
    # the bulk backlog still pending
    s.run_once(timeout=0.05)
    urgent = [r for r in s.results if r.pod.name == "urgent"]
    assert urgent and urgent[0].node is not None
    assert len(q) > 100  # bulk still deep: express did not wait it out


def test_bulk_batch_requeued_when_express_cycle_raises():
    """The bulk batch popped just before the express interleave is held
    only in run_once's frame: an express-cycle failure must requeue it
    (popped pods are never lost), not strand it Pending forever."""
    from kubernetes_tpu.runtime.queue import PodBackoff

    cache = _cluster()
    q = PriorityQueue(backoff=PodBackoff(initial=0.01, max_duration=0.02))
    s = _sched(cache=cache, queue=q, express_lane=True,
               express_priority_threshold=1000, batch_size=8)
    for i in range(5):
        q.add(make_pod(f"b{i}", cpu="10m"))
    q.add(make_pod("e0", cpu="10m", priority=2000))

    def boom():
        raise RuntimeError("express blew up")

    s._run_express = boom
    with pytest.raises(RuntimeError):
        s.run_once(timeout=0.05)
    # every popped bulk pod is back in the queue (parked unschedulable)
    assert len(q) >= 5
    del s._run_express
    q.move_all_to_active()  # the cluster-event revival path
    while len(q):
        s.run_once(timeout=0.1)
    placed = {r.pod.name for r in s.results if r.node is not None}
    assert {f"b{i}" for i in range(5)} <= placed


# --------------------------------------------------------------- bit-identity


@pytest.mark.parametrize("engine", ["sequential", "speculative"])
def test_interleaved_placements_bit_identical_to_single_lane(engine):
    """The tiered run's placements must equal a single-lane scheduler
    replaying the SAME pop order (express batch as its own cycle, then
    the bulk batch): the express lane changes WHEN pods schedule, never
    WHERE."""
    def pods():
        bulk = [
            make_pod(f"b{i}", cpu="500m", mem="1Gi",
                     labels={"app": "web"},
                     node_selector={"tier": "a"} if i % 3 == 0 else None)
            for i in range(12)
        ]
        exp = [
            make_pod(f"e{i}", cpu="500m", mem="1Gi",
                     labels={"app": "web"}, priority=2000 + (i % 2))
            for i in range(5)
        ]
        return bulk, exp

    # tiered run: queue admission classifies, run_once interleaves
    cache_a = _cluster()
    qa = PriorityQueue()
    sa = _sched(cache=cache_a, queue=qa, engine=engine, express_lane=True,
                express_priority_threshold=1000, express_batch_size=8,
                batch_size=16)
    bulk, exp = pods()
    for p in bulk:
        qa.add(p)
    for p in exp:
        qa.add(p)
    sa.run_once(timeout=0.2)
    placed_a = {r.pod.name: r.node for r in sa.results}

    # single-lane replay of the same pop order: express pods first (the
    # lane's priority-FIFO order), then the bulk batch
    cache_b = _cluster()
    sb = _sched(cache=cache_b, engine=engine, batch_size=16)
    bulk_b, exp_b = pods()
    exp_order = sorted(exp_b, key=lambda p: -p.spec.priority)
    for r in sb.schedule_cycle(exp_order):
        pass
    sb.schedule_cycle(bulk_b)
    placed_b = {r.pod.name: r.node for r in sb.results}

    assert placed_a == placed_b, (
        f"tiered vs single-lane diverged: "
        f"{ {k: (placed_a.get(k), placed_b.get(k)) for k in placed_a if placed_a.get(k) != placed_b.get(k)} }"
    )
    assert all(v is not None for v in placed_a.values())


# ------------------------------------------------- prewarm + width helper


def test_aimd_pow2_widths():
    assert aimd_pow2_widths(16, 256) == [16, 32, 64, 128, 256]
    assert aimd_pow2_widths(16, 16) == [16]
    # non-pow2 ends round UP to the encode pad widths actually compiled
    assert aimd_pow2_widths(12, 100) == [16, 32, 64, 128]
    assert aimd_pow2_widths(1, 4) == [1, 2, 4]
    # floor above the cap clamps to the cap — never an empty ladder
    assert aimd_pow2_widths(16, 8) == [8]


def test_prewarm_compiles_without_perturbing_state():
    cache = _cluster()
    q = PriorityQueue()
    s = _sched(cache=cache, queue=q, express_lane=True,
               express_priority_threshold=1000, express_batch_size=8,
               batch_size=16, adaptive_batch=True, batch_size_min=4)
    timings = s.prewarm()
    # the AIMD ladder (4..16) plus the express width (8, already inside)
    assert sorted(timings) == [4, 8, 16]
    assert all(t >= 0 for t in timings.values())
    assert s._last_index == 0  # rotation untouched
    assert len(s.results) == 0
    # placements after prewarm match a never-prewarmed scheduler (the
    # adaptive pop width is 4, so replay single-lane cycles of 4)
    for i in range(8):
        q.add(make_pod(f"p{i}", cpu="100m", labels={"app": "web"}))
    while len(q):
        s.run_once(timeout=0.05)
    placed = {r.pod.name: r.node for r in s.results}

    s2 = _sched(cache=_cluster(), batch_size=16)
    replay = [
        make_pod(f"p{i}", cpu="100m", labels={"app": "web"})
        for i in range(8)
    ]
    s2.schedule_cycle(replay[:4])
    s2.schedule_cycle(replay[4:])
    placed2 = {r.pod.name: r.node for r in s2.results}
    assert placed == placed2


def test_express_width_does_not_grow_sticky_dims():
    cache = _cluster()
    enc = cache.encoder
    q = PriorityQueue()
    s = _sched(cache=cache, queue=q, express_lane=True,
               express_priority_threshold=1000, express_batch_size=4,
               batch_size=64)
    # a bulk cycle grows the sticky pad width...
    for i in range(20):
        q.add(make_pod(f"b{i}", cpu="10m"))
    s.run_once(timeout=0.1)
    bulk_b = enc.dims.B
    assert bulk_b >= 20
    # ...but an express cycle encodes at ITS width without growing dims.B
    q.add(make_pod("e0", cpu="10m", priority=2000))
    s.run_once(timeout=0.1)
    assert enc.dims.B == bulk_b
    assert [r.node for r in s.results if r.pod.name == "e0"] != [None]
    with enc.batch_width(4):
        assert enc.batch_pad(1) == 4
        assert enc.batch_pad(9) == 16  # overflow still pads correctly
    assert enc.batch_pad(1) == bulk_b  # override restored


# ------------------------------------------------------------- compile cache


def test_compile_cache_knob(tmp_path, monkeypatch):
    from kubernetes_tpu.utils import compilecache as cc

    # every resolved directory carries the topology partition tag
    # (ISSUE 9: a cache written single-chip is never served to a sharded
    # process); under the 8-virtual-device test mesh the tag is cpu-d8
    tag = cc.topology_tag()
    assert tag.startswith("cpu")

    # precedence: explicit arg > env > default; "off" disables
    monkeypatch.delenv(cc.CACHE_DIR_ENV, raising=False)
    assert cc.resolve_cache_dir(None) == os.path.join(
        cc.DEFAULT_CACHE_DIR, tag
    )
    monkeypatch.setenv(cc.CACHE_DIR_ENV, str(tmp_path / "env"))
    assert cc.resolve_cache_dir(None) == os.path.join(
        str(tmp_path / "env"), tag
    )
    assert cc.resolve_cache_dir(str(tmp_path / "arg")) == os.path.join(
        str(tmp_path / "arg"), tag
    )
    assert cc.resolve_cache_dir("off") is None
    monkeypatch.setenv(cc.CACHE_DIR_ENV, "off")
    assert cc.resolve_cache_dir(None) is None
    monkeypatch.delenv(cc.CACHE_DIR_ENV, raising=False)

    import jax

    prev = jax.config.jax_compilation_cache_dir
    try:
        d = cc.enable_compile_cache(str(tmp_path / "cache"))
        assert d == os.path.join(str(tmp_path / "cache"), tag)
        assert os.path.isdir(d)
        assert jax.config.jax_compilation_cache_dir == d
        assert cc.enable_compile_cache("off") is None
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
